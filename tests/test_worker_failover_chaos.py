"""Crash-safe generation: cross-worker sequence failover chaos suite.

The acceptance scenario: a client streams tokens from worker A over the
direct SSE path; A checkpoints the generation to the control plane
(admission + per-token cadence + heartbeat piggyback); a seeded fault kills
A's socket mid-stream (after ≥1 delivered token, before EOS); the SDK
reconnects with its ``Last-Event-ID``-style offset, worker B adopts the
checkpoint (epoch fence bumps, zombifying A), resumes via
``TPUEngine.resume`` and splices the continuation — and the client ends up
with the BYTE-IDENTICAL greedy token sequence an unkilled run produces.
No gap, no duplicate, across 25 seeds.

Also covered here: assignment-epoch fencing of a zombie's late
complete_job / stale checkpoints, drain migration without retry burn,
partial-output preservation on permanent failure, the
``TaskGuaranteeService.wait_for_job`` timeout and ``_lost_race`` paths
(previously untested), the HandoffReceiver adopt-session cap, and the
SDK's consumed-prefix fallback guard.
"""

import asyncio
import json
import threading
import time
from typing import Any, Dict, List, Optional

import httpx
import pytest

from distributed_gpu_inference_tpu.runtime.engine import PreemptedSequence
from distributed_gpu_inference_tpu.sdk.client import (
    InferenceClient,
    InferenceClientError,
)
from distributed_gpu_inference_tpu.server.store import Store
from distributed_gpu_inference_tpu.server.task_guarantee import (
    TaskGuaranteeService,
)
from distributed_gpu_inference_tpu.testing import faults
from distributed_gpu_inference_tpu.testing.faults import FaultPlan, FaultRule
from distributed_gpu_inference_tpu.testing.harness import LiveControlPlane
from distributed_gpu_inference_tpu.utils.data_structures import (
    InferenceRequest,
    JobStatus,
    SamplingParams,
    WorkerState,
)
from distributed_gpu_inference_tpu.worker.api_client import APIClient, APIError

pytestmark = [pytest.mark.chaos, pytest.mark.failover]

N_SEEDS = 25


def _wire(prompt: List[int], generated: List[int], max_new: int = 16,
          request_id: str = "r1") -> Dict[str, Any]:
    """A valid v1 checkpoint for control-plane-level tests."""
    return PreemptedSequence(
        request=InferenceRequest(
            request_id=request_id,
            prompt_token_ids=list(prompt),
            sampling=SamplingParams(max_new_tokens=max_new),
        ),
        prompt_len=len(prompt),
        generated=list(generated),
        slot_key=(3, 4),
        start_time=1.0,
        first_token_time=2.0,
        cached_tokens=0,
    ).to_wire()


# ---------------------------------------------------------------------------
# end-to-end: kill worker A mid-stream, client splices B's continuation
# ---------------------------------------------------------------------------


class _DirectWorker:
    """Minimal worker shim around a real TPULLMEngine + DirectServer: the
    claim state machine, the checkpoint sink (stream cadence → control
    plane), and stream adoption — the exact surfaces ``Worker`` wires."""

    def __init__(self, eng: Any, api: APIClient) -> None:
        self.engines = {"llm": eng}
        self.api = api
        self.state = WorkerState.IDLE
        self.adoptions = 0
        eng.checkpoint_sink = self.push_stream_checkpoint

    def try_begin_job(self) -> bool:
        if self.state != WorkerState.IDLE:
            return False
        self.state = WorkerState.BUSY
        return True

    def end_job(self) -> None:
        if self.state == WorkerState.BUSY:
            self.state = WorkerState.IDLE

    def should_accept_job(self, job: Dict[str, Any]) -> bool:
        return True

    def note_job_done(self, started: float) -> None:
        pass

    def get_status(self) -> Dict[str, Any]:
        return {"state": self.state.value}

    def adopt_stream_checkpoint(self, stream_id: str
                                ) -> Optional[Dict[str, Any]]:
        try:
            out = self.api.adopt_stream(stream_id)
        except APIError as exc:
            if exc.status == 404:
                return None
            raise
        self.adoptions += 1
        return out

    def push_stream_checkpoint(self, entry: Dict[str, Any]) -> None:
        if entry.get("kind") != "stream":
            return
        self.api.checkpoint_stream(
            entry["key"], int(entry.get("epoch") or 0),
            entry.get("state"), done=bool(entry.get("done")),
        )


class _Fleet:
    """One live control plane + two direct workers (A first in discovery
    order) sharing tiny real engines; built once per module — jit compiles
    amortize across the 25 seeds."""

    def __init__(self) -> None:
        from distributed_gpu_inference_tpu.worker.direct_server import (
            DirectServer,
        )
        from distributed_gpu_inference_tpu.worker.engines.llm import (
            TPULLMEngine,
        )

        self.plane = LiveControlPlane()
        self.plane.__enter__()
        self.workers: List[_DirectWorker] = []
        self.servers = []
        for name in ("wka", "wkb"):
            eng = TPULLMEngine({
                "model": "llama3-tiny", "max_batch_size": 2,
                "max_seq_len": 128, "multi_step": 4,
                # per-token cadence: the kill point is seeded per event, so
                # a checkpoint must exist before every possible cut
                "checkpoint_interval_tokens": 1,
            })
            eng.load_model()
            api = APIClient(self.plane.url, backoff_s=0.0)
            w = _DirectWorker(eng, api)
            ds = DirectServer(w, host="127.0.0.1", port=0)
            ds.start()
            port = ds._runner.addresses[0][1]
            api.register({
                "name": name, "region": "us-west",
                "supported_types": ["llm"],
                "supports_direct": True,
                "direct_url": f"http://127.0.0.1:{port}",
            })
            self.workers.append(w)
            self.servers.append(ds)

    def close(self) -> None:
        for ds in self.servers:
            ds.stop()
        for w in self.workers:
            w.api.close()
        self.plane.__exit__(None, None, None)


@pytest.fixture(scope="module")
def fleet():
    f = _Fleet()
    yield f
    f.close()


def _collect(chunks: List[Dict[str, Any]]) -> Dict[str, Any]:
    toks: List[int] = []
    text = ""
    for c in chunks:
        if c.get("done"):
            return {"tokens": toks, "text": text,
                    "finish": c.get("finish_reason"),
                    "usage": c.get("usage", {})}
        toks.extend(c.get("token_ids") or [])
        text += c.get("text_delta") or ""
    raise AssertionError("stream ended without a done event")


def _scenario_prompt(seed: int) -> str:
    return "".join(chr(97 + (seed * 7 + i * 3) % 26) for i in range(12))


def scenario_kill_mid_stream(fleet: _Fleet, seed: int) -> None:
    a, b = fleet.workers
    max_new = 10 + seed % 5
    prompt = _scenario_prompt(seed)
    params = {"prompt": prompt, "max_new_tokens": max_new}
    # reference: the same greedy generation, unkilled, straight off worker
    # B's engine (identically-seeded weights; its prefix cache then also
    # exercises the KV-restore-on-resume path in the kill run)
    ref = _collect(list(b.engines["llm"].stream(dict(params))))
    n = len(ref["tokens"])
    if n < 2:
        # degenerate seed (EOS at the first token): nothing to kill
        # mid-generation — lengthen the prompt deterministically
        params["prompt"] = prompt + "qz"
        ref = _collect(list(b.engines["llm"].stream(dict(params))))
        n = len(ref["tokens"])
    assert n >= 2, f"seed {seed}: reference produced {n} tokens"
    # kill after k delivered events, 1 ≤ k ≤ n-1: ≥1 token reached the
    # client, and the cut lands strictly before the last token event (so
    # before EOS/done)
    kill_after = 1 + (seed % (n - 1))
    plan = FaultPlan(seed, [
        FaultRule(site="worker.direct.stream", kind="drop",
                  after=kill_after, times=1),
    ])
    adoptions_before = b.adoptions
    client = InferenceClient(fleet.plane.url, backoff_s=0.0)
    try:
        with faults.active(plan):
            out = _collect(list(client.stream_chat(timeout_s=60.0, **params)))
    finally:
        client.close()
    # the kill fired exactly once, and the failover worker adopted
    assert [t[1] for t in plan.trace] == ["drop"], (seed, plan.trace)
    assert b.adoptions == adoptions_before + 1, seed
    # exactly-once: byte-identical token sequence — no gap, no duplicate
    assert out["tokens"] == ref["tokens"], (
        seed, kill_after, out["tokens"], ref["tokens"]
    )
    assert out["text"] == ref["text"], (seed, kill_after)
    assert out["finish"] == ref["finish"], (seed, kill_after)
    # both engines quiet (no leaked slots on either side of the failover);
    # the server-side release runs in the handler's finally, which races
    # the client's read of the final event — give it a moment to land
    deadline = time.time() + 5.0
    while time.time() < deadline and not (
        a.state == WorkerState.IDLE and b.state == WorkerState.IDLE
        and a.engines["llm"].engine.num_active == 0
        and b.engines["llm"].engine.num_active == 0
    ):
        time.sleep(0.01)
    assert a.engines["llm"].engine.num_active == 0
    assert b.engines["llm"].engine.num_active == 0
    assert a.state == WorkerState.IDLE and b.state == WorkerState.IDLE


@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_kill_mid_stream_byte_identical_continuation(fleet, seed):
    scenario_kill_mid_stream(fleet, seed)


def test_completed_stream_checkpoint_stays_until_sweep(fleet):
    """A worker cannot know its final SSE bytes reached the client, so a
    completed stream's checkpoint stays adoptable (a tail-less client can
    still resume); the control-plane sweep ages it out instead."""
    b = fleet.workers[1]
    client = InferenceClient(fleet.plane.url, backoff_s=0.0)
    try:
        chunks = list(client.stream_chat(prompt="hello world",
                                         max_new_tokens=6, timeout_s=60.0))
    finally:
        client.close()
    out = _collect(chunks)
    assert out["finish"] in ("stop", "length")
    sid = next(c["stream_id"] for c in chunks if c.get("stream_id"))
    adopted = b.api.adopt_stream(sid)
    assert adopted["checkpoint"]["v"] == 1
    # ...and the age sweep retires abandoned rows
    cp = fleet.plane
    purged = cp.call(cp.state.guarantee.sweep_stale_stream_checkpoints(
        now=time.time() + 31 * 60.0
    ))
    assert sid in purged
    with pytest.raises(APIError) as ei:
        b.api.adopt_stream(sid)
    assert ei.value.status == 404


# ---------------------------------------------------------------------------
# control-plane fencing: epochs, zombies, drain migration
# ---------------------------------------------------------------------------


def _register(cp: LiveControlPlane, name: str) -> APIClient:
    api = APIClient(cp.url, backoff_s=0.0)
    api.register({"name": name, "region": "us-west",
                  "supported_types": ["llm"]})
    return api


def _create_job(cp: LiveControlPlane,
                params: Optional[Dict[str, Any]] = None) -> str:
    return cp.call(cp.state.store.create_job({
        "type": "llm", "params": params or {"prompt": "x"},
    }))


def test_job_checkpoint_rides_heartbeat_and_is_epoch_fenced():
    with LiveControlPlane() as cp:
        api_a = _register(cp, "a")
        job_id = _create_job(cp)
        job = api_a.fetch_next_job()
        assert job["id"] == job_id
        assert int(job["assignment_epoch"]) == 1      # claim bumped it
        assert job.get("checkpoint") is None

        ck1 = _wire([1, 2, 3], [10, 11])
        api_a.heartbeat(status="busy", current_job_id=job_id, checkpoints=[
            {"kind": "job", "key": job_id, "epoch": 1, "state": ck1},
        ])
        row = cp.job(job_id)
        assert row["checkpoint"]["generated"] == [10, 11]

        # stale-epoch checkpoint is fenced out (heartbeat still succeeds)
        ck_stale = _wire([1, 2, 3], [99])
        api_a.heartbeat(status="busy", current_job_id=job_id, checkpoints=[
            {"kind": "job", "key": job_id, "epoch": 0, "state": ck_stale},
        ])
        assert cp.job(job_id)["checkpoint"]["generated"] == [10, 11]

        # worker dies: requeue PRESERVES the checkpoint, burns one retry
        cp.call(cp.state.guarantee.handle_worker_offline(api_a.worker_id))
        row = cp.job(job_id)
        assert row["status"] == JobStatus.QUEUED.value
        assert row["checkpoint"]["generated"] == [10, 11]
        assert row["retry_count"] == 1

        # replacement worker's claim carries the checkpoint + a NEW epoch
        api_b = _register(cp, "b")
        job_b = api_b.fetch_next_job()
        assert job_b["id"] == job_id
        assert int(job_b["assignment_epoch"]) == 2
        assert job_b["checkpoint"]["generated"] == [10, 11]

        # cross-worker zombie: A's late completion bounces on the
        # ownership check (the pre-existing fence)
        with pytest.raises(APIError) as ei:
            api_a.complete_job(job_id, success=True,
                               result={"text": "zombie"},
                               assignment_epoch=1)
        assert ei.value.status == 404
        assert cp.job(job_id)["worker_id"] == api_b.worker_id

        # SAME-worker zombie — the hole only the epoch closes: B's job is
        # requeued, B revives and RECLAIMS it (epoch 3); B's previous
        # incarnation then reports under epoch 2 — worker_id matches,
        # status is RUNNING, but the fence rejects it with 409
        cp.call(cp.state.guarantee.handle_worker_offline(api_b.worker_id))
        api_b.heartbeat(status="idle")           # revive
        job_b2 = api_b.fetch_next_job()
        assert job_b2["id"] == job_id
        assert int(job_b2["assignment_epoch"]) == 3
        with pytest.raises(APIError) as ei:
            api_b.complete_job(job_id, success=True,
                               result={"text": "zombie"},
                               assignment_epoch=2)
        assert ei.value.status == 409
        row = cp.job(job_id)
        assert row["status"] == JobStatus.RUNNING.value
        assert row.get("result") is None

        # the live incarnation's completion (current epoch) applies
        api_b.complete_job(job_id, success=True, result={"text": "ok"},
                           assignment_epoch=3)
        row = cp.job(job_id)
        assert row["status"] == JobStatus.COMPLETED.value
        assert row["result"]["text"] == "ok"
        api_a.close()
        api_b.close()


def test_drain_migration_requeues_with_checkpoint_no_retry_burn():
    with LiveControlPlane() as cp:
        api = _register(cp, "a")
        job_id = _create_job(cp)
        job = api.fetch_next_job()
        out = api.checkpoint_job(job_id, int(job["assignment_epoch"]),
                                 _wire([1, 2], [7, 8, 9]), migrate=True)
        assert out["requeued"] is True
        row = cp.job(job_id)
        assert row["status"] == JobStatus.QUEUED.value
        assert row["retry_count"] == 0           # a drain is not a failure
        assert row["checkpoint"]["generated"] == [7, 8, 9]
        assert row["worker_id"] is None
        w = cp.worker(api.worker_id)
        assert w["current_job_id"] is None

        # a second (now stale-epoch) migrate attempt is fenced
        with pytest.raises(APIError) as ei:
            api.checkpoint_job(job_id, int(job["assignment_epoch"]),
                               _wire([1, 2], [7]), migrate=True)
        assert ei.value.status in (404, 409)
        api.close()


def test_stream_checkpoint_adopt_bumps_epoch_and_fences_zombie():
    with LiveControlPlane() as cp:
        api_a = _register(cp, "a")
        api_b = _register(cp, "b")
        sid = "stream-1"
        ck = _wire([1, 2, 3], [5])
        assert api_a.checkpoint_stream(sid, 0, ck)["ok"] is True

        adopted = api_b.adopt_stream(sid)
        assert adopted["epoch"] == 1
        assert adopted["checkpoint"]["generated"] == [5]

        # zombie A: stale checkpoint rejected, stale "done" cannot erase
        with pytest.raises(APIError) as ei:
            api_a.checkpoint_stream(sid, 0, _wire([1, 2, 3], [5, 6]))
        assert ei.value.status == 409
        api_a.checkpoint_stream(sid, 0, None, done=True)
        assert cp.call(cp.state.store.get_stream_checkpoint(sid)) is not None

        # the adopter keeps checkpointing at its epoch, then retires it
        assert api_b.checkpoint_stream(
            sid, 1, _wire([1, 2, 3], [5, 6, 7])
        )["ok"] is True
        api_b.checkpoint_stream(sid, 1, None, done=True)
        assert cp.call(cp.state.store.get_stream_checkpoint(sid)) is None
        with pytest.raises(APIError) as ei:
            api_b.adopt_stream(sid)
        assert ei.value.status == 404
        api_a.close()
        api_b.close()


def test_nearest_direct_worker_exclude_filters_the_corpse():
    with LiveControlPlane() as cp:
        for name in ("a", "b"):
            api = APIClient(cp.url, backoff_s=0.0)
            api.register({
                "name": name, "region": "us-west",
                "supported_types": ["llm"], "supports_direct": True,
                "direct_url": f"http://{name}.example:8471",
            })
            if name == "a":
                wid_a = api.worker_id
            api.close()
        r = httpx.get(f"{cp.url}/api/v1/jobs/direct/nearest")
        assert r.json()["worker_id"] == wid_a
        r = httpx.get(f"{cp.url}/api/v1/jobs/direct/nearest",
                      params={"exclude": wid_a})
        assert r.json()["worker_id"] != wid_a


# ---------------------------------------------------------------------------
# task-guarantee satellites: partial preservation, wait_for_job, lost races
# ---------------------------------------------------------------------------


def test_permanent_failure_preserves_checkpoint_partial_output():
    async def body():
        store = Store()
        svc = TaskGuaranteeService(store)
        job_id = await store.create_job({
            "type": "llm", "params": {}, "status": JobStatus.RUNNING.value,
            "worker_id": "w1", "started_at": time.time(),
            "retry_count": 3, "max_retries": 3,
            "checkpoint": _wire([1, 2], [21, 22, 23]),
        })
        job = await store.get_job(job_id)
        status = await svc.requeue_job(job, reason="worker_offline")
        assert status == JobStatus.FAILED.value
        row = await store.get_job(job_id)
        assert row["result"]["partial"] is True
        assert row["result"]["partial_token_ids"] == [21, 22, 23]
        assert row["result"]["partial_tokens"] == 3
        assert "max_retries" in row["error"]
        store.close()

    asyncio.run(body())


def test_requeue_without_checkpoint_keeps_no_partial():
    async def body():
        store = Store()
        svc = TaskGuaranteeService(store)
        job_id = await store.create_job({
            "type": "llm", "params": {}, "status": JobStatus.RUNNING.value,
            "worker_id": "w1", "started_at": time.time(),
            "retry_count": 3, "max_retries": 3,
        })
        job = await store.get_job(job_id)
        assert await svc.requeue_job(job) == JobStatus.FAILED.value
        assert (await store.get_job(job_id)).get("result") is None
        store.close()

    asyncio.run(body())


def test_wait_for_job_times_out_returns_last_row():
    async def body():
        store = Store()
        svc = TaskGuaranteeService(store)
        job_id = await store.create_job({"type": "llm", "params": {}})
        t0 = time.monotonic()
        row = await svc.wait_for_job(job_id, timeout_s=0.2, poll_s=0.02)
        assert time.monotonic() - t0 >= 0.2
        # non-terminal at the deadline: the CURRENT row comes back, so the
        # caller can report the live status instead of a generic timeout
        assert row is not None and row["status"] == JobStatus.QUEUED.value
        store.close()

    asyncio.run(body())


def test_wait_for_job_missing_job_returns_none_and_terminal_returns():
    async def body():
        store = Store()
        svc = TaskGuaranteeService(store)
        assert await svc.wait_for_job("nope", timeout_s=0.1,
                                      poll_s=0.02) is None
        job_id = await store.create_job({"type": "llm", "params": {}})

        async def complete_soon():
            await asyncio.sleep(0.05)
            await store.update_job(job_id, status=JobStatus.COMPLETED.value)

        task = asyncio.ensure_future(complete_soon())
        row = await svc.wait_for_job(job_id, timeout_s=5.0, poll_s=0.02)
        await task
        assert row["status"] == JobStatus.COMPLETED.value
        store.close()

    asyncio.run(body())


def test_requeue_lost_race_returns_live_status():
    async def body():
        store = Store()
        svc = TaskGuaranteeService(store)
        job_id = await store.create_job({
            "type": "llm", "params": {}, "status": JobStatus.RUNNING.value,
            "worker_id": "w1", "started_at": time.time(),
        })
        snapshot = await store.get_job(job_id)
        # a slow-but-alive worker completes JUST before the sweep's write:
        # the conditional transition loses and the terminal status wins
        await store.update_job(job_id, status=JobStatus.COMPLETED.value)
        status = await svc.requeue_job(snapshot, reason="job_timeout")
        assert status == JobStatus.COMPLETED.value
        assert (await store.get_job(job_id))["status"] == \
            JobStatus.COMPLETED.value

        # and a job deleted out from under the sweep reports FAILED
        job2 = await store.create_job({
            "type": "llm", "params": {}, "status": JobStatus.RUNNING.value,
            "worker_id": "w1", "started_at": time.time(),
        })
        snap2 = await store.get_job(job2)
        await store.execute("DELETE FROM jobs WHERE id=?", (job2,))
        assert await svc.requeue_job(snap2) == JobStatus.FAILED.value
        store.close()

    asyncio.run(body())


def test_requeue_lost_write_takes_lost_race_path():
    """Chaos seam: the conditional transition's write is DROPPED (wedged
    store) — requeue_job must report the row's live status, not pretend
    the requeue happened."""
    async def body():
        store = Store()
        svc = TaskGuaranteeService(store)
        job_id = await store.create_job({
            "type": "llm", "params": {}, "status": JobStatus.RUNNING.value,
            "worker_id": "w1", "started_at": time.time(),
        })
        job = await store.get_job(job_id)
        plan = FaultPlan(0, [
            FaultRule(site="server.store.execute", kind="drop",
                      match={"sql": "*transition*"}),
        ])
        with faults.active(plan):
            status = await svc.requeue_job(job)
        assert status == JobStatus.RUNNING.value   # nothing moved
        assert plan.trace
        store.close()

    asyncio.run(body())


# ---------------------------------------------------------------------------
# worker-side: process_job context, drain migration, heartbeat piggyback
# ---------------------------------------------------------------------------


class _FakeFailoverEngine:
    supports_failover = True

    def __init__(self) -> None:
        self.seen_ctx: List[Dict[str, Any]] = []
        self.migrate_on_next: Optional[Dict[str, Any]] = None
        self.live_entries: List[Dict[str, Any]] = []
        self.interrupted = False

    def inference(self, params: Dict[str, Any]) -> Dict[str, Any]:
        from distributed_gpu_inference_tpu.worker.engines.base import (
            JobMigrated,
        )

        self.seen_ctx.append(params.get("_failover_ctx"))
        if self.migrate_on_next is not None:
            ck, self.migrate_on_next = self.migrate_on_next, None
            raise JobMigrated(ck, tokens=len(ck.get("generated") or []))
        return {"text": "done"}

    def checkpoint_live(self) -> List[Dict[str, Any]]:
        return list(self.live_entries)

    def interrupt_live(self) -> None:
        self.interrupted = True


class _FakeCkptAPI:
    def __init__(self) -> None:
        self.worker_id = "w-1"
        self.completed: List[Dict[str, Any]] = []
        self.checkpointed: List[Dict[str, Any]] = []
        self.heartbeats: List[Dict[str, Any]] = []

    def heartbeat(self, **kw):
        self.heartbeats.append(kw)
        return {}

    def complete_job(self, job_id, success, result=None, error=None,
                     **kw):
        self.completed.append({"job_id": job_id, "success": success,
                               "result": result, "error": error, **kw})
        return {"ok": True}

    def checkpoint_job(self, job_id, assignment_epoch, state,
                       migrate=False):
        self.checkpointed.append({
            "job_id": job_id, "assignment_epoch": assignment_epoch,
            "state": state, "migrate": migrate,
        })
        return {"ok": True, "requeued": True}

    def going_offline(self):
        pass


def _worker_with(engine, api):
    from distributed_gpu_inference_tpu.utils.config import WorkerConfig
    from distributed_gpu_inference_tpu.worker.main import Worker

    w = Worker(WorkerConfig(), api=api)
    w.engines = {"llm": engine}
    w.state = WorkerState.IDLE
    return w


def test_process_job_threads_failover_ctx_and_epoch():
    eng, api = _FakeFailoverEngine(), _FakeCkptAPI()
    w = _worker_with(eng, api)
    assert w.try_begin_job()
    ck = _wire([1], [2])
    w.process_job({"id": "j1", "type": "llm", "params": {"prompt": "x"},
                   "assignment_epoch": 3, "checkpoint": ck})
    ctx = eng.seen_ctx[0]
    assert ctx == {"key": "j1", "kind": "job", "epoch": 3, "checkpoint": ck}
    assert api.completed[0]["assignment_epoch"] == 3
    assert w.state == WorkerState.IDLE


def test_process_job_without_epoch_keeps_legacy_complete():
    eng, api = _FakeFailoverEngine(), _FakeCkptAPI()
    w = _worker_with(eng, api)
    assert w.try_begin_job()
    w.process_job({"id": "j1", "type": "llm", "params": {}})
    assert "assignment_epoch" not in api.completed[0]


def test_job_migrated_checkpoints_instead_of_completing():
    eng, api = _FakeFailoverEngine(), _FakeCkptAPI()
    ck = _wire([1], [2, 3])
    eng.migrate_on_next = ck
    w = _worker_with(eng, api)
    assert w.try_begin_job()
    w.process_job({"id": "j1", "type": "llm", "params": {},
                   "assignment_epoch": 2})
    assert api.completed == []
    assert api.checkpointed == [{
        "job_id": "j1", "assignment_epoch": 2, "state": ck, "migrate": True,
    }]
    assert w.stats["jobs_migrated"] == 1
    assert w.stats["jobs_failed"] == 0


def test_heartbeat_piggybacks_live_checkpoints_and_drain_interrupts():
    eng, api = _FakeFailoverEngine(), _FakeCkptAPI()
    entry = {"kind": "job", "key": "j1", "epoch": 1, "state": _wire([1], [2])}
    eng.live_entries = [entry]
    w = _worker_with(eng, api)
    w._heartbeat_once()
    assert api.heartbeats[0]["checkpoints"] == [entry]
    eng.live_entries = []
    w._heartbeat_once()
    assert "checkpoints" not in api.heartbeats[1]
    w.request_shutdown()
    assert eng.interrupted


# ---------------------------------------------------------------------------
# llm-engine unit: queued resume from a checkpoint is byte-identical
# ---------------------------------------------------------------------------


def test_job_inference_resumes_from_checkpoint_byte_identical(fleet):
    from distributed_gpu_inference_tpu.worker.engines.base import (
        GenerationConfig,
    )

    llm = fleet.workers[1].engines["llm"]
    params = {"prompt": "resume me please", "max_new_tokens": 9}
    ref = _collect(list(llm.stream(dict(params))))
    assert len(ref["tokens"]) >= 4, ref
    # rebuild the request EXACTLY as the engine did (eos merged into stops)
    req = llm._build_request(params["prompt"],
                             GenerationConfig.from_params(params))
    # pretend the first worker died after 3 tokens: checkpoint carries them
    pre = PreemptedSequence(
        request=req, prompt_len=len(req.prompt_token_ids),
        generated=ref["tokens"][:3],
        slot_key=(0, 0), start_time=time.time(), first_token_time=None,
        cached_tokens=0,
    )
    resumed = llm.inference({**params,
                             "_failover_ctx": {"key": "jf2", "epoch": 2,
                                               "checkpoint": pre.to_wire()}})
    assert resumed["text"] == ref["text"]
    assert resumed["usage"]["completion_tokens"] == \
        ref["usage"]["completion_tokens"]
    assert llm.engine.num_active == 0


def test_interrupt_freezes_queued_job_into_checkpoint(fleet):
    from distributed_gpu_inference_tpu.worker.engines.base import JobMigrated

    llm = fleet.workers[0].engines["llm"]
    llm._interrupt.set()
    try:
        with pytest.raises(JobMigrated) as ei:
            llm.inference({"prompt": "drain mid-generation",
                           "max_new_tokens": 32,
                           "_failover_ctx": {"key": "jd", "epoch": 1,
                                             "checkpoint": None}})
    finally:
        llm._interrupt.clear()
    ck = ei.value.checkpoint
    assert ck["v"] == 1
    assert isinstance(ck["generated"], list)
    assert llm.engine.num_active == 0
    # the frozen state resumes cleanly elsewhere (worker B's engine)
    other = fleet.workers[1].engines["llm"]
    resumed = other.inference({"prompt": "drain mid-generation",
                               "max_new_tokens": 32,
                               "_failover_ctx": {"key": "jd2", "epoch": 2,
                                                 "checkpoint": ck}})
    reference = other.inference({"prompt": "drain mid-generation",
                                 "max_new_tokens": 32})
    assert resumed["text"] == reference["text"]


# ---------------------------------------------------------------------------
# HandoffReceiver: adopt-session cap purge (satellite)
# ---------------------------------------------------------------------------


def test_handoff_begin_purges_on_session_cap():
    from distributed_gpu_inference_tpu.runtime.kv_handoff import (
        HandoffReceiver,
    )
    from distributed_gpu_inference_tpu.testing.fakes import (
        FakeKVEngine,
        make_stream_messages,
    )

    eng = FakeKVEngine(num_blocks=64)
    rx = HandoffReceiver(eng)
    rx.MAX_SESSIONS = 2
    rx.handle(make_stream_messages("k1", list(range(8)))[0])
    rx.handle(make_stream_messages("k2", list(range(8, 16)))[0])
    free_before = len(eng.manager.free_blocks)
    # third begin: the table is at the cap — the stalest session (k1) is
    # evicted, its blocks freed, and the purge is COUNTED
    rx.handle(make_stream_messages("k3", list(range(16, 24)))[0])
    assert "k1" not in rx._sessions
    assert {"k2", "k3"} <= set(rx._sessions)
    assert rx.stats["sessions_purged"] == 1
    assert len(eng.manager.free_blocks) >= free_before - 2
    with pytest.raises(ValueError, match="no streamed handoff session"):
        rx.handle(make_stream_messages("k1", list(range(8)))[1])


def test_handoff_ttl_purge_is_counted():
    from distributed_gpu_inference_tpu.runtime.kv_handoff import (
        HandoffReceiver,
    )
    from distributed_gpu_inference_tpu.testing.fakes import (
        FakeKVEngine,
        make_stream_messages,
    )

    eng = FakeKVEngine(num_blocks=32)
    rx = HandoffReceiver(eng)
    rx.handle(make_stream_messages("k1", list(range(8)))[0])
    rx._sessions["k1"].last_activity -= rx.SESSION_TTL_S + 1.0
    rx._purge_stale()
    assert rx.stats["sessions_purged"] == 1


# ---------------------------------------------------------------------------
# SDK: fallback guard + resume protocol (satellite + tentpole client half)
# ---------------------------------------------------------------------------


class _IterStream(httpx.SyncByteStream):
    def __init__(self, it):
        self._it = it

    def __iter__(self):
        return self._it


def _sse(chunk: Dict[str, Any]) -> bytes:
    return f"data: {json.dumps(chunk)}\n\n".encode()


def test_sdk_resumes_dropped_stream_and_splices():
    calls: List[Dict[str, Any]] = []

    class T(httpx.BaseTransport):
        def handle_request(self, req):
            if req.url.path == "/api/v1/jobs/direct/nearest":
                excl = dict(req.url.params).get("exclude", "")
                wid = "wb" if "wa" in excl else "wa"
                return httpx.Response(200, json={
                    "worker_id": wid, "region": "us-west",
                    "direct_url": f"http://{wid}:8471",
                })
            assert req.url.path == "/inference/stream"
            body = json.loads(req.read())
            calls.append(body)
            if "resume" not in body:
                def gen():
                    yield _sse({"text_delta": "He", "token_ids": [1],
                                "offset": 1, "stream_id": "s"})
                    yield _sse({"text_delta": "ll", "token_ids": [2],
                                "offset": 2, "stream_id": "s"})
                    raise httpx.ReadError("worker died")

                return httpx.Response(
                    200, headers={"Content-Type": "text/event-stream"},
                    stream=_IterStream(gen()),
                )
            assert body["resume"] == {"stream_id": body["stream_id"],
                                      "offset": 2, "text_offset": 4}
            sse = (_sse({"text_delta": "o", "token_ids": [3], "offset": 3,
                         "stream_id": "s"})
                   + _sse({"done": True, "finish_reason": "stop",
                           "usage": {"completion_tokens": 3}, "offset": 3}))
            return httpx.Response(
                200, content=sse,
                headers={"Content-Type": "text/event-stream"},
            )

    c = InferenceClient("http://s1", transport=T(), backoff_s=0.0)
    chunks = list(c.stream_chat(prompt="x"))
    assert "".join(ch.get("text_delta", "") for ch in chunks[:-1]) == "Hello"
    assert [t for ch in chunks[:-1] for t in ch["token_ids"]] == [1, 2, 3]
    assert chunks[-1]["done"] is True
    # the reconnect excluded the dead worker and went to the failover peer
    assert len(calls) == 2 and "resume" in calls[1]


def test_sdk_no_checkpoint_after_consumption_raises_never_requeues():
    """Satellite guard: once a chunk was consumed, a dropped stream must
    NEVER fall back to a fresh queued job (double generation) — with no
    checkpoint to resume from, it raises."""
    queued_calls = []

    class T(httpx.BaseTransport):
        def handle_request(self, req):
            if req.url.path == "/api/v1/jobs/direct/nearest":
                return httpx.Response(200, json={
                    "worker_id": "wa", "region": "us-west",
                    "direct_url": "http://wa:8471",
                })
            if req.url.path in ("/api/v1/jobs/sync", "/api/v1/jobs"):
                queued_calls.append(req.url.path)
                return httpx.Response(200, json={
                    "job_id": "j", "status": "completed",
                    "result": {"text": "dup"},
                })
            assert req.url.path == "/inference/stream"
            body = json.loads(req.read())
            if "resume" in body:
                return httpx.Response(409, json={
                    "detail": "no checkpoint for stream",
                })

            def gen():
                yield _sse({"text_delta": "He", "token_ids": [1],
                            "offset": 1})
                raise httpx.ReadError("worker died")

            return httpx.Response(
                200, headers={"Content-Type": "text/event-stream"},
                stream=_IterStream(gen()),
            )

    c = InferenceClient("http://s1", transport=T(), backoff_s=0.0)
    out = []
    with pytest.raises(InferenceClientError,
                       match="no checkpoint to resume"):
        for ch in c.stream_chat(prompt="x"):
            out.append(ch)
    assert out and out[0]["text_delta"] == "He"
    assert queued_calls == []           # the prompt never re-ran


def test_sdk_drop_before_first_chunk_still_falls_back_to_queue():
    class T(httpx.BaseTransport):
        def handle_request(self, req):
            if req.url.path == "/api/v1/jobs/direct/nearest":
                return httpx.Response(200, json={
                    "worker_id": "wa", "region": "us-west",
                    "direct_url": "http://wa:8471",
                })
            if req.url.path == "/inference/stream":
                raise httpx.ConnectError("refused")
            assert req.url.path == "/api/v1/jobs/sync"
            return httpx.Response(200, json={
                "job_id": "j", "status": "completed",
                "result": {"text": "fallback", "finish_reason": "stop",
                           "usage": {"completion_tokens": 1}},
            })

    c = InferenceClient("http://s1", transport=T(), backoff_s=0.0)
    chunks = list(c.stream_chat(prompt="x"))
    assert chunks[0]["text_delta"] == "fallback"
    assert chunks[-1]["done"] is True


def test_sdk_passes_same_offset_holdback_flush_chunk():
    """An EOS finish flushes held-back stop-string characters as a
    text-only chunk at an UNCHANGED token offset — the dedupe must let it
    through (only same-offset chunks carrying token ids are replays)."""
    class T(httpx.BaseTransport):
        def handle_request(self, req):
            if req.url.path == "/api/v1/jobs/direct/nearest":
                return httpx.Response(200, json={
                    "worker_id": "wa", "region": "us-west",
                    "direct_url": "http://wa:8471",
                })
            assert req.url.path == "/inference/stream"
            sse = (_sse({"text_delta": "Hel", "token_ids": [1, 2],
                         "offset": 2})
                   + _sse({"text_delta": "lo", "token_ids": [],
                           "offset": 2})          # holdback flush
                   + _sse({"done": True, "finish_reason": "stop",
                           "usage": {"completion_tokens": 2}, "offset": 2}))
            return httpx.Response(
                200, content=sse,
                headers={"Content-Type": "text/event-stream"},
            )

    c = InferenceClient("http://s1", transport=T(), backoff_s=0.0)
    chunks = list(c.stream_chat(prompt="x", stop=["###"]))
    assert "".join(ch.get("text_delta", "") for ch in chunks[:-1]) == "Hello"
    assert chunks[-1]["done"] is True


def test_sdk_resume_sends_text_offset_and_worker_splices_flush(fleet):
    """Resume after a holdback flush: the client's consumed TEXT is ahead
    of what the token offset implies; the resume body carries text_offset
    and the worker's splice never re-delivers the flushed characters."""
    llm = fleet.workers[1].engines["llm"]
    params = {"prompt": "stop string splice", "max_new_tokens": 8,
              "stop": ["ÿÿÿ"]}      # never matches: holdback 2
    ref = _collect(list(llm.stream(dict(params))))
    assert ref["tokens"], ref
    # simulate: client consumed everything (tokens AND flushed text), then
    # the done event was lost — it resumes with full offsets
    from distributed_gpu_inference_tpu.worker.engines.base import (
        GenerationConfig,
    )

    req = llm._build_request(params["prompt"],
                             GenerationConfig.from_params(params))
    pre = PreemptedSequence(
        request=req, prompt_len=len(req.prompt_token_ids),
        generated=ref["tokens"], slot_key=(0, 0),
        start_time=time.time(), first_token_time=None, cached_tokens=0,
    )
    out = list(llm.stream({**params, "_failover_ctx": {
        "key": "sf", "epoch": 2, "checkpoint": pre.to_wire(),
        "offset": len(ref["tokens"]), "text_offset": len(ref["text"]),
    }}))
    resumed = _collect(out)
    # everything was already consumed — NOTHING may be re-delivered (the
    # flushed holdback characters in particular), and the stream closes
    # with the same finish
    assert resumed["text"] == ""
    assert resumed["tokens"] == []
    assert resumed["finish"] == ref["finish"]


def test_sdk_resume_budget_exhaustion_raises():
    class T(httpx.BaseTransport):
        def handle_request(self, req):
            if req.url.path == "/api/v1/jobs/direct/nearest":
                return httpx.Response(200, json={
                    "worker_id": "wa", "region": "us-west",
                    "direct_url": "http://wa:8471",
                })
            assert req.url.path == "/inference/stream"

            def gen():
                yield _sse({"text_delta": "x", "token_ids": [1],
                            "offset": 1})
                raise httpx.ReadError("flaky")

            return httpx.Response(
                200, headers={"Content-Type": "text/event-stream"},
                stream=_IterStream(gen()),
            )

    c = InferenceClient("http://s1", transport=T(), backoff_s=0.0)
    with pytest.raises(InferenceClientError, match="resume budget"):
        # every reconnect re-yields nothing new (offset 1 deduped) then
        # drops again — the budget bounds the loop
        list(c.stream_chat(prompt="x", max_stream_resumes=2))


# ---------------------------------------------------------------------------
# wire format: versioning
# ---------------------------------------------------------------------------


def test_checkpoint_wire_rejects_unknown_version():
    ck = _wire([1, 2], [3])
    ck["v"] = 99
    with pytest.raises(ValueError, match="unsupported checkpoint version"):
        PreemptedSequence.from_wire(ck)
    with pytest.raises(ValueError):
        PreemptedSequence.from_wire("not-a-dict")


def test_checkpoint_wire_roundtrips_through_json():
    ck = json.loads(json.dumps(_wire([1, 2, 3], [9, 8])))
    pre = PreemptedSequence.from_wire(ck)
    assert pre.generated == [9, 8]
    assert pre.slot_key == (3, 4)
    assert pre.request.prompt_token_ids == [1, 2, 3]
    assert pre.request.sampling.max_new_tokens == 16
