"""Weight-only quantization (int8/fp8): roundtrip accuracy, model forward
parity, engine integration, TP sharding, and pipeline stage slicing.

Reference parity: vLLM quantization passthrough flags
(``worker/engines/llm_vllm.py:83-87`` AWQ/GPTQ/FP8/INT8) — here the scheme is
first-party (``ops/quantization.py``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# compile-heavy (jit/scan graphs): excluded from the fast CI gate
pytestmark = pytest.mark.slow

from distributed_gpu_inference_tpu.models import llama
from distributed_gpu_inference_tpu.models.configs import get_model_config
from distributed_gpu_inference_tpu.ops import quantization as q
from distributed_gpu_inference_tpu.runtime.engine import EngineConfig, TPUEngine
from distributed_gpu_inference_tpu.utils.data_structures import (
    InferenceRequest,
    SamplingParams,
)


# ---------------------------------------------------------------- roundtrip


@pytest.mark.parametrize("mode,tol", [("int8", 0.01), ("fp8", 0.04)])
def test_roundtrip_error_bounded(mode, tol):
    w = jax.random.normal(jax.random.PRNGKey(0), (3, 64, 48), jnp.float32)
    qw = q.quantize_weight(w, mode)
    assert qw["qw"].shape == w.shape
    assert qw["scale"].shape == (3, 1, 48)
    back = q.dequantize(qw)
    rel = float(jnp.max(jnp.abs(back - w)) / jnp.max(jnp.abs(w)))
    assert rel < tol


def test_int8_storage_dtype_and_bytes():
    w = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32), jnp.bfloat16)
    qw = q.quantize_weight(w, "int8")
    assert qw["qw"].dtype == jnp.int8
    assert qw["scale"].dtype == jnp.float32
    # int8 payload is half the bf16 bytes (scales are negligible)
    assert qw["qw"].nbytes == w.nbytes // 2


def test_zero_channel_safe():
    w = jnp.zeros((1, 8, 8), jnp.float32)
    qw = q.quantize_weight(w, "int8")
    assert np.all(np.asarray(q.dequantize(qw)) == 0.0)


def test_matmul_dispatch_plain_and_quantized():
    key = jax.random.PRNGKey(2)
    x = jax.random.normal(key, (4, 64), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(3), (64, 32), jnp.float32) * 0.05
    exact = x @ w
    approx = q.matmul(x, q.quantize_weight(w, "int8"))
    assert q.matmul(x, w).shape == approx.shape == exact.shape
    err = float(jnp.max(jnp.abs(approx - exact)))
    scale = float(jnp.max(jnp.abs(exact))) + 1e-9
    assert err / scale < 0.02


def test_unknown_mode_raises():
    with pytest.raises(ValueError):
        q.quantize_weight(jnp.ones((2, 2)), "awq")


# ------------------------------------------------------------- model parity


def _forward_last_logits(cfg, params, tokens):
    b, s = tokens.shape
    kv = llama.init_kv_pools(cfg, num_blocks=8, block_size=16,
                             dtype=jnp.float32)
    tables = np.tile(np.arange(1, 5, dtype=np.int32), (b, 1))
    positions = np.tile(np.arange(s, dtype=np.int32), (b, 1))
    out = llama.forward_chunk(
        cfg, params, jnp.asarray(tokens), jnp.asarray(positions), kv,
        jnp.asarray(tables), jnp.full((b,), s, jnp.int32),
        block_size=16, last_only=True,
    )
    return np.asarray(out.logits[:, 0, :])


@pytest.mark.parametrize("mode,tol,min_cos", [
    ("int8", 0.08, 0.999),
    ("fp8", 0.25, 0.99),   # e4m3: 3 mantissa bits → ~6% per-element step
])
def test_forward_parity_quantized_vs_full(mode, tol, min_cos):
    cfg = get_model_config("llama3-tiny", dtype="float32")
    params = llama.init_params(cfg, jax.random.PRNGKey(0), jnp.float32)
    tokens = np.array([[5, 17, 3, 99, 42, 7, 256, 31]], np.int32)
    full = _forward_last_logits(cfg, params, tokens)
    quant = _forward_last_logits(cfg, q.quantize_params(params, mode), tokens)
    # a random-init model has near-uniform logits — the hardest case for
    # argmax stability, so parity is asserted on the logit field itself
    denom = np.max(np.abs(full)) + 1e-9
    assert np.max(np.abs(full - quant)) / denom < tol
    cos = float(
        np.dot(full.ravel(), quant.ravel())
        / (np.linalg.norm(full) * np.linalg.norm(quant) + 1e-9)
    )
    assert cos > min_cos


def test_quantize_params_structure_and_bytes():
    cfg = get_model_config("llama3-mini")
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    qp = q.quantize_params(params, "int8")
    for k in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
        assert q.is_quantized(qp["layers"][k])
    # norms/embedding untouched; None is identity
    assert qp["layers"]["attn_norm"] is params["layers"]["attn_norm"]
    assert qp["embedding"] is params["embedding"]
    assert q.quantize_params(params, None) is params
    assert q.param_bytes(qp) < 0.7 * q.param_bytes(params)


# ---------------------------------------------------------------- engine e2e


@pytest.mark.parametrize("mode", ["int8", "fp8"])
def test_engine_generates_quantized(mode):
    eng = TPUEngine(
        "llama3-tiny",
        EngineConfig(max_batch_size=2, max_seq_len=64, block_size=16,
                     prefill_buckets=(16,), dtype="float32",
                     quantization=mode),
    )
    reqs = [
        InferenceRequest(
            prompt_token_ids=[5, 17, 3, 99, 42],
            sampling=SamplingParams(max_new_tokens=8, temperature=0.0),
        )
    ]
    outs = eng.generate(reqs)
    assert len(outs) == 1
    assert len(outs[0].token_ids) == 8
    assert all(0 <= t < eng.model_cfg.vocab_size for t in outs[0].token_ids)
    assert q.param_bytes(eng.params) < 0.7 * q.param_bytes(
        llama.init_params(eng.model_cfg, jax.random.PRNGKey(0), jnp.float32)
    )


def test_engine_quantized_matches_full_greedy():
    """Greedy decode: int8 engine should emit the same tokens as full
    precision on the tiny model (ample logit margins at random init)."""
    def run(quant):
        eng = TPUEngine(
            "llama3-tiny",
            EngineConfig(max_batch_size=1, max_seq_len=64, block_size=16,
                         prefill_buckets=(16,), dtype="float32",
                         quantization=quant),
            seed=0,
        )
        out = eng.generate([
            InferenceRequest(
                prompt_token_ids=[5, 17, 3, 99, 42, 7, 256, 31],
                sampling=SamplingParams(max_new_tokens=10, temperature=0.0),
            )
        ])
        return out[0].token_ids

    # near-uniform random-init logits eventually diverge under quantization
    # noise; the leading tokens must still agree
    assert run(None)[:6] == run("int8")[:6]


# -------------------------------------------------------- sharding / stages


def test_tp_sharded_quantized_engine(cpu_devices):
    from distributed_gpu_inference_tpu.parallel.mesh import MeshPlan, make_mesh

    mesh = make_mesh(MeshPlan(model=2), cpu_devices[:2],
                     keep_trivial_axes=False)
    eng = TPUEngine(
        "llama3-tiny",
        EngineConfig(max_batch_size=2, max_seq_len=64, block_size=16,
                     prefill_buckets=(16,), dtype="float32",
                     quantization="int8"),
        mesh=mesh,
    )
    out = eng.generate([
        InferenceRequest(
            prompt_token_ids=[5, 17, 3, 99, 42],
            sampling=SamplingParams(max_new_tokens=6, temperature=0.0),
        )
    ])
    assert len(out[0].token_ids) == 6
    # qw really sharded over the model axis (out-dim split in two)
    qw = eng.params["layers"]["wq"]["qw"]
    shard_shape = qw.sharding.shard_shape(qw.shape)
    assert shard_shape[-1] == qw.shape[-1] // 2


def test_pipeline_stage_slicing_quantized():
    from distributed_gpu_inference_tpu.parallel.pipeline import (
        slice_stage_params,
    )

    cfg = get_model_config("llama3-mini")
    params = q.quantize_params(
        llama.init_params(cfg, jax.random.PRNGKey(0)), "int8"
    )
    s0 = slice_stage_params(params, 0, 2, num_layers=cfg.num_layers)
    s1 = slice_stage_params(params, 2, 4, num_layers=cfg.num_layers)
    assert s0["layers"]["wq"]["qw"].shape[0] == 2
    assert s0["layers"]["wq"]["scale"].shape[0] == 2
    assert s1["layers"]["w_down"]["qw"].shape[0] == 2
    assert "embedding" in s0 and "final_norm" in s1


def test_engine_quant_cache_roundtrip(tmp_path):
    """quant_cache_dir persists the quantized tree on first build (VERDICT
    r2 #1: cold starts must not re-quantize); a second engine restores it
    bit-exactly and produces identical greedy tokens."""
    import numpy as np

    from distributed_gpu_inference_tpu.runtime.engine import (
        EngineConfig,
        TPUEngine,
    )
    from distributed_gpu_inference_tpu.utils.data_structures import (
        InferenceRequest,
        SamplingParams,
    )

    cfg = EngineConfig(
        max_batch_size=2, max_seq_len=64, prefill_buckets=(16, 32),
        quantization="int8", quant_cache_dir=str(tmp_path / "qc"),
        dtype="float32",
    )
    e1 = TPUEngine("llama3-tiny", cfg)
    cache_dirs = list((tmp_path / "qc").iterdir())
    assert len(cache_dirs) == 1 and (cache_dirs[0] / "params").exists()

    e2 = TPUEngine("llama3-tiny", cfg)
    for a, b in zip(
        sorted(jax.tree_util.tree_leaves_with_path(e1.params), key=str),
        sorted(jax.tree_util.tree_leaves_with_path(e2.params), key=str),
    ):
        np.testing.assert_array_equal(np.asarray(a[1]), np.asarray(b[1]))

    req = InferenceRequest(
        prompt_token_ids=list(range(10, 30)),
        sampling=SamplingParams(max_new_tokens=6, temperature=0.0),
    )
    r1 = e1.generate([req])[0]
    r2 = e2.generate([InferenceRequest(
        prompt_token_ids=list(range(10, 30)),
        sampling=SamplingParams(max_new_tokens=6, temperature=0.0),
    )])[0]
    assert r1.token_ids == r2.token_ids


def test_init_quantized_streamed_matches_reference_structure():
    """Streamed on-device quantized init (the 8B cold-start path) produces
    the exact pytree layout quantize_params(init_params(...)) does, is
    deterministic across calls, and serves a forward pass."""
    import numpy as np

    from distributed_gpu_inference_tpu.models import llama
    from distributed_gpu_inference_tpu.models.configs import get_model_config
    from distributed_gpu_inference_tpu.models.loader import (
        init_quantized_streamed,
    )

    cfg = get_model_config("llama3-tiny")
    p = init_quantized_streamed(cfg, "int8", dtype="float32")
    ref = q.quantize_params(
        llama.init_params(cfg, jax.random.PRNGKey(0), jnp.float32), "int8"
    )
    assert jax.tree.structure(p) == jax.tree.structure(ref)
    for (k1, a), (k2, b) in zip(
        sorted(jax.tree_util.tree_leaves_with_path(p), key=str),
        sorted(jax.tree_util.tree_leaves_with_path(ref), key=str),
    ):
        assert a.shape == b.shape and a.dtype == b.dtype, (k1, k2)
    p2 = init_quantized_streamed(cfg, "int8", dtype="float32")
    for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
