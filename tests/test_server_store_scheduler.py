"""Store / scheduler / reliability / task-guarantee tests.

Mirrors the reference's hermetic server tests (sqlite in-memory instead of
Postgres — SURVEY §4: ``tests/conftest.py:7``), exercising the reconstructed
§2.1 schema, the atomic job claim, score-based ranking, reliability deltas,
and the requeue/sweep machinery.
"""

import asyncio
import time

import pytest

from distributed_gpu_inference_tpu.server.reliability import ReliabilityService
from distributed_gpu_inference_tpu.server.scheduler import (
    SmartScheduler,
    estimate_job_duration_s,
    region_distance,
)
from distributed_gpu_inference_tpu.server.store import Store
from distributed_gpu_inference_tpu.server.task_guarantee import (
    TaskGuaranteeService,
)
from distributed_gpu_inference_tpu.utils.data_structures import (
    JobStatus,
    WorkerState,
)


def run(coro):
    return asyncio.run(coro)


def _worker(wid="w1", **kw):
    base = {
        "id": wid,
        "name": wid,
        "region": "us-west",
        "supported_types": ["llm"],
        "status": WorkerState.IDLE.value,
        "last_heartbeat": time.time(),
        "num_chips": 4,
    }
    base.update(kw)
    return base


def _job(**kw):
    base = {"type": "llm", "params": {"max_new_tokens": 64}}
    base.update(kw)
    return base


# ---------------------------------------------------------------------------
# store
# ---------------------------------------------------------------------------


def test_worker_roundtrip_json_fields():
    async def body():
        s = Store()
        await s.upsert_worker(_worker(loaded_models=["llama3-8b"],
                                      online_pattern={"3": 0.7}))
        w = await s.get_worker("w1")
        assert w["supported_types"] == ["llm"]
        assert w["loaded_models"] == ["llama3-8b"]
        assert w["online_pattern"] == {"3": 0.7}
        await s.update_worker("w1", status=WorkerState.BUSY.value)
        assert (await s.get_worker("w1"))["status"] == "busy"
        s.close()

    run(body())


def test_job_crud_and_listing_order():
    async def body():
        s = Store()
        low = await s.create_job(_job(priority=0))
        high = await s.create_job(_job(priority=5))
        jobs = await s.list_jobs(status=[JobStatus.QUEUED.value])
        assert [j["id"] for j in jobs] == [high, low]  # priority DESC
        await s.update_job(low, status=JobStatus.CANCELLED.value)
        assert (await s.get_job(low))["status"] == "cancelled"
        s.close()

    run(body())


def test_claim_next_job_atomic_and_filtered():
    async def body():
        s = Store()
        await s.upsert_worker(_worker())
        jid = await s.create_job(_job())
        await s.create_job({"type": "image_gen", "params": {}})
        got = await s.claim_next_job("w1", ["llm"], region="us-west")
        assert got["id"] == jid and got["status"] == JobStatus.RUNNING.value
        assert got["worker_id"] == "w1"
        # second claim: only the image_gen job remains, not supported
        assert await s.claim_next_job("w1", ["llm"], region="us-west") is None
        s.close()

    run(body())


def test_claim_respects_cross_region_restriction():
    async def body():
        s = Store()
        await s.create_job(
            _job(preferred_region="eu-west", allow_cross_region=False)
        )
        assert await s.claim_next_job("w1", ["llm"], region="us-west") is None
        got = await s.claim_next_job("w2", ["llm"], region="eu-west")
        assert got is not None
        s.close()

    run(body())


def test_concurrent_claims_unique():
    """Two workers claiming concurrently never get the same job."""

    async def body():
        s = Store()
        ids = [await s.create_job(_job()) for _ in range(4)]
        got = await asyncio.gather(
            *[s.claim_next_job(f"w{i}", ["llm"]) for i in range(6)]
        )
        claimed = [g["id"] for g in got if g is not None]
        assert sorted(claimed) == sorted(ids)
        assert len(set(claimed)) == len(claimed)
        s.close()

    run(body())


# ---------------------------------------------------------------------------
# scheduler
# ---------------------------------------------------------------------------


def test_region_distance_matrix_symmetric_zero_diag():
    assert region_distance("us-west", "us-west") == 0
    assert region_distance("us-west", "eu-west") == region_distance(
        "eu-west", "us-west"
    )


def test_duration_estimator_scales_with_chips():
    d1 = estimate_job_duration_s("llm", {"max_new_tokens": 300}, num_chips=1)
    d4 = estimate_job_duration_s("llm", {"max_new_tokens": 300}, num_chips=4)
    assert d4 < d1
    assert estimate_job_duration_s("image_gen", {"num_inference_steps": 50}) > 3


def test_scheduler_ranks_by_score():
    async def body():
        s = Store()
        await s.upsert_worker(
            _worker("good", reliability_score=0.9, region="us-west")
        )
        await s.upsert_worker(
            _worker("bad", reliability_score=0.1, region="asia-east")
        )
        sched = SmartScheduler(s)
        ranked = await sched.rank_workers(
            {"type": "llm", "preferred_region": "us-west"}
        )
        assert [w["id"] for w in ranked] == ["good", "bad"]
        s.close()

    run(body())


def test_atomic_assign_marks_worker_busy():
    async def body():
        s = Store()
        await s.upsert_worker(_worker())
        await s.create_job(_job())
        sched = SmartScheduler(s)
        job = await sched.atomic_assign_job("w1")
        assert job is not None
        w = await s.get_worker("w1")
        assert w["status"] == WorkerState.BUSY.value
        assert w["current_job_id"] == job["id"]
        # draining workers get nothing
        await s.update_worker("w1", status=WorkerState.DRAINING.value)
        await s.create_job(_job())
        assert await sched.atomic_assign_job("w1") is None
        s.close()

    run(body())


def test_queue_stats_wait_estimate():
    async def body():
        s = Store()
        sched = SmartScheduler(s)
        stats = await sched.get_queue_stats()
        assert stats["active_workers"] == 0
        await s.upsert_worker(_worker())
        await s.create_job(_job())
        stats = await sched.get_queue_stats()
        assert stats["queued"] == 1
        assert stats["estimated_wait_s"] > 0
        assert stats["total_chips"] == 4
        s.close()

    run(body())


# ---------------------------------------------------------------------------
# reliability
# ---------------------------------------------------------------------------


def test_reliability_score_deltas_and_clamp():
    async def body():
        s = Store()
        await s.upsert_worker(_worker(reliability_score=0.5))
        r = ReliabilityService(s)
        sc = await r.record_event("w1", "job_completed", latency_ms=500.0)
        # +0.02 complete, +0.01 fast response
        assert sc == pytest.approx(0.53)
        w = await s.get_worker("w1")
        assert w["completed_jobs"] == 1 and w["success_rate"] == 1.0
        for _ in range(20):
            sc = await r.record_event("w1", "unexpected_offline")
        assert sc == 0.0  # clamped
        s.close()

    run(body())


def test_session_tracking_updates_averages():
    async def body():
        s = Store()
        await s.upsert_worker(_worker())
        r = ReliabilityService(s)
        t0 = 1000.0
        await r.start_session("w1", now=t0)
        minutes = await r.end_session("w1", graceful=True, now=t0 + 1200)
        assert minutes == pytest.approx(20.0)
        w = await s.get_worker("w1")
        assert w["total_sessions"] == 1
        assert w["avg_session_minutes"] == pytest.approx(20.0)
        assert w["total_online_seconds"] == pytest.approx(1200.0)
        s.close()

    run(body())


def test_online_pattern_ema_and_prediction():
    async def body():
        s = Store()
        await s.upsert_worker(_worker())
        r = ReliabilityService(s)
        now = time.time()
        for _ in range(10):
            await r.update_online_pattern("w1", online=True, now=now)
        w = await s.get_worker("w1")
        p = r.predict_online_probability(w, now=now)
        assert p > 0.6  # strong online history this hour
        s.close()

    run(body())


def test_predict_remaining_online_time():
    async def body():
        s = Store()
        await s.upsert_worker(
            _worker(avg_session_minutes=30.0, current_session_start=1000.0)
        )
        r = ReliabilityService(s)
        w = await s.get_worker("w1")
        rem = r.predict_remaining_online_time(w, now=1000.0 + 600)
        assert rem == pytest.approx(20.0)
        s.close()

    run(body())


# ---------------------------------------------------------------------------
# task guarantee
# ---------------------------------------------------------------------------


def test_requeue_until_max_retries():
    async def body():
        s = Store()
        g = TaskGuaranteeService(s)
        jid = await s.create_job(_job(max_retries=2))
        job = await s.get_job(jid)
        assert await g.requeue_job(job) == JobStatus.QUEUED.value
        job = await s.get_job(jid)
        assert job["retry_count"] == 1
        job["retry_count"] = 2
        await s.update_job(jid, retry_count=2)
        job = await s.get_job(jid)
        assert await g.requeue_job(job) == JobStatus.FAILED.value
        assert "max_retries" in (await s.get_job(jid))["error"]
        s.close()

    run(body())


def test_worker_offline_requeues_running_jobs():
    async def body():
        s = Store()
        g = TaskGuaranteeService(s)
        await s.upsert_worker(_worker())
        jid = await s.create_job(_job())
        await s.claim_next_job("w1", ["llm"])
        requeued = await g.handle_worker_offline("w1")
        assert requeued == [jid]
        assert (await s.get_job(jid))["status"] == JobStatus.QUEUED.value
        assert (await s.get_worker("w1"))["status"] == WorkerState.OFFLINE.value
        s.close()

    run(body())


def test_sweep_dead_workers_and_stale_jobs():
    async def body():
        s = Store()
        g = TaskGuaranteeService(s, heartbeat_timeout_s=90.0)
        now = time.time()
        await s.upsert_worker(_worker("dead", last_heartbeat=now - 1000))
        await s.upsert_worker(_worker("alive", last_heartbeat=now))
        jid = await s.create_job(_job(timeout_seconds=10.0))
        await s.claim_next_job("alive", ["llm"])
        await s.update_job(jid, started_at=now - 60)  # past its 10 s timeout
        result = await g.sweep(now=now)
        assert result["dead_workers"] == ["dead"]
        assert result["stale_jobs"] == [jid]
        s.close()

    run(body())


def test_wait_for_job_returns_on_completion():
    async def body():
        s = Store()
        g = TaskGuaranteeService(s)
        jid = await s.create_job(_job())

        async def complete_later():
            await asyncio.sleep(0.05)
            await s.update_job(jid, status=JobStatus.COMPLETED.value,
                               result={"text": "hi"})

        task = asyncio.get_running_loop().create_task(complete_later())
        job = await g.wait_for_job(jid, timeout_s=2.0, poll_s=0.01)
        await task
        assert job["status"] == JobStatus.COMPLETED.value
        assert job["result"] == {"text": "hi"}
        s.close()

    run(body())


def test_stale_job_requeue_frees_worker_capacity():
    """Regression: a timed-out job must not leave a phantom BUSY worker."""

    async def body():
        s = Store()
        g = TaskGuaranteeService(s)
        now = time.time()
        await s.upsert_worker(_worker(last_heartbeat=now))
        jid = await s.create_job(_job(timeout_seconds=10.0))
        await s.claim_next_job("w1", ["llm"])
        await s.update_worker("w1", current_job_id=jid,
                              status=WorkerState.BUSY.value)
        await s.update_job(jid, started_at=now - 60)
        swept = await g.sweep_stale_jobs(now=now)
        assert swept == [jid]
        w = await s.get_worker("w1")
        assert w["current_job_id"] is None
        assert w["status"] == WorkerState.IDLE.value
        s.close()

    run(body())


def test_dead_worker_penalty_applied_once():
    """Regression: unexpected_offline must not be double-counted per sweep."""

    async def body():
        s = Store()
        g = TaskGuaranteeService(s, heartbeat_timeout_s=90.0)
        now = time.time()
        await s.upsert_worker(
            _worker("dead", last_heartbeat=now - 1000,
                    reliability_score=0.5, current_session_start=now - 2000)
        )
        await g.sweep_dead_workers(now=now)
        w = await s.get_worker("dead")
        assert w["unexpected_offline_count"] == 1
        assert w["reliability_score"] == pytest.approx(0.35)  # one -0.15 delta
        s.close()

    run(body())


def test_claim_scans_past_region_restricted_head():
    """Regression: 20+ cross-region-locked jobs at the head must not starve
    claimable jobs behind them."""

    async def body():
        s = Store()
        for _ in range(25):
            await s.create_job(
                _job(priority=5, preferred_region="eu-west",
                     allow_cross_region=False)
            )
        jid = await s.create_job(_job(priority=0))
        got = await s.claim_next_job("w1", ["llm"], region="us-west")
        assert got is not None and got["id"] == jid
        s.close()

    run(body())


# -- schema migrations (PRAGMA user_version runner) -------------------------


def test_fresh_db_lands_at_schema_version(tmp_path):
    from distributed_gpu_inference_tpu.server import store as store_mod

    s = Store(str(tmp_path / "fresh.sqlite"))
    ver = s._conn.execute("PRAGMA user_version").fetchone()[0]
    assert ver == store_mod.SCHEMA_VERSION
    # v2 column exists on a fresh db too (fresh files replay migrations)
    cols = [r[1] for r in s._conn.execute("PRAGMA table_info(jobs)")]
    assert "enterprise_id" in cols
    s.close()


def test_migrates_legacy_v1_file_in_place(tmp_path):
    import sqlite3

    from distributed_gpu_inference_tpu.server import store as store_mod

    path = str(tmp_path / "legacy.sqlite")
    # a legacy pre-versioning database: v1 tables, user_version 0, plus a row
    conn = sqlite3.connect(path)
    conn.executescript(store_mod._SCHEMA)
    conn.execute(
        "INSERT INTO jobs (id, type, created_at) VALUES ('j1', 'llm', 1.0)"
    )
    conn.commit()
    assert conn.execute("PRAGMA user_version").fetchone()[0] == 0
    conn.close()

    s = Store(path)
    assert (
        s._conn.execute("PRAGMA user_version").fetchone()[0]
        == store_mod.SCHEMA_VERSION
    )
    # old data survived, new column usable
    s._conn.execute(
        "UPDATE jobs SET enterprise_id='e1' WHERE id='j1'"
    )
    row = s._conn.execute(
        "SELECT enterprise_id FROM jobs WHERE id='j1'"
    ).fetchone()
    assert row[0] == "e1"
    s.close()


def test_reopen_at_current_version_is_noop(tmp_path):
    path = str(tmp_path / "db.sqlite")
    s1 = Store(path)
    run(s1.create_job({"id": "j1", "type": "llm"}))
    s1.close()
    s2 = Store(path)  # must not raise or re-apply
    assert run(s2.get_job("j1"))["type"] == "llm"
    s2.close()


def test_newer_db_refused(tmp_path):
    import sqlite3

    path = str(tmp_path / "future.sqlite")
    conn = sqlite3.connect(path)
    conn.execute("PRAGMA user_version=9999")
    conn.commit()
    conn.close()
    with pytest.raises(RuntimeError, match="newer"):
        Store(path)
