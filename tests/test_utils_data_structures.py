"""Substrate invariants (parity: reference tests/test_common_data_structures.py)."""

import time

import pytest

from distributed_gpu_inference_tpu.utils.data_structures import (
    BlockRange,
    InferenceState,
    JobStatus,
    KVBlockMeta,
    ModelShardConfig,
    SamplingParams,
    TpuTopology,
    WorkerInfo,
    WorkerRole,
    WorkerState,
    compute_prefix_hash,
    estimate_kv_cache_bytes,
)


class TestBlockRange:
    def test_basic(self):
        r = BlockRange(0, 8)
        assert r.num_layers == 8
        assert 0 in r and 7 in r and 8 not in r

    def test_invalid(self):
        with pytest.raises(ValueError):
            BlockRange(5, 3)
        with pytest.raises(ValueError):
            BlockRange(-1, 3)

    def test_overlap(self):
        assert BlockRange(0, 4).overlaps(BlockRange(3, 8))
        assert not BlockRange(0, 4).overlaps(BlockRange(4, 8))

    def test_roundtrip(self):
        r = BlockRange(2, 9)
        assert BlockRange.from_dict(r.to_dict()) == r


class TestWorkerInfo:
    def test_availability(self):
        w = WorkerInfo(state=WorkerState.IDLE, max_sessions=2)
        assert w.is_available
        w.active_sessions = 2
        assert not w.is_available
        w.state = WorkerState.DRAINING
        assert not w.is_available

    def test_staleness(self):
        w = WorkerInfo()
        assert not w.is_stale(90.0)
        assert w.is_stale(90.0, now=w.last_heartbeat + 91)

    def test_roundtrip(self):
        w = WorkerInfo(
            role=WorkerRole.PREFILL,
            layer_range=BlockRange(0, 16),
            topology=TpuTopology(chip_type="v5p", num_chips=4, mesh_shape=(2, 2),
                                 mesh_axis_names=("data", "model")),
        )
        w2 = WorkerInfo.from_dict(w.to_dict())
        assert w2.role == WorkerRole.PREFILL
        assert w2.layer_range == BlockRange(0, 16)
        assert w2.topology.mesh_shape == (2, 2)
        assert w2.topology.total_hbm_gb == w.topology.total_hbm_gb


class TestInferenceState:
    def test_token_accounting(self):
        st = InferenceState(max_new_tokens=3)
        t0 = st.created_at
        st.record_token(now=t0 + 0.1)
        assert st.ttft_ms == pytest.approx(100.0, rel=0.01)
        st.record_token(now=t0 + 0.2)
        st.record_token(now=t0 + 0.3)
        assert st.finished and st.finish_reason == "length"
        assert st.generated_tokens == 3
        assert st.tpot_ms == pytest.approx(100.0, rel=0.01)


class TestKVBlockMeta:
    def test_refcount_cow(self):
        b = KVBlockMeta(block_id=0)
        assert not b.is_shared
        assert b.incref() == 2
        assert b.is_shared
        assert b.decref() == 1
        assert b.decref() == 0
        with pytest.raises(ValueError):
            b.decref()

    def test_capacity(self):
        b = KVBlockMeta(block_id=1, num_tokens=16)
        assert b.is_full


class TestModelShardConfig:
    def _cfg(self):
        return ModelShardConfig(
            model_name="llama3-8b",
            num_layers=32,
            stages=[BlockRange(0, 11), BlockRange(11, 22), BlockRange(22, 32)],
            stage_workers=["w0", "w1", "w2"],
        )

    def test_route(self):
        route = self._cfg().get_inference_route()
        assert [w for w, _ in route] == ["w0", "w1", "w2"]
        assert route[-1][1].end == 32

    def test_stage_for_layer(self):
        cfg = self._cfg()
        assert cfg.stage_for_layer(0) == 0
        assert cfg.stage_for_layer(11) == 1
        assert cfg.stage_for_layer(31) == 2

    def test_validation_gap(self):
        with pytest.raises(ValueError):
            ModelShardConfig(
                model_name="m", num_layers=32,
                stages=[BlockRange(0, 10), BlockRange(12, 32)],
            )

    def test_validation_incomplete(self):
        with pytest.raises(ValueError):
            ModelShardConfig(
                model_name="m", num_layers=32,
                stages=[BlockRange(0, 10), BlockRange(10, 30)],
            )


def test_prefix_hash_stability_and_prefix_property():
    a = compute_prefix_hash([1, 2, 3, 4])
    assert a == compute_prefix_hash([1, 2, 3, 4])
    assert a != compute_prefix_hash([1, 2, 3, 5])
    assert a == compute_prefix_hash([1, 2, 3, 4, 9, 9], upto=4)


def test_kv_size_estimate():
    # llama3-8b-ish: 32 layers, 8 kv heads, 128 head_dim, 4096 seq, bf16
    n = estimate_kv_cache_bytes(32, 8, 128, 4096, 2)
    assert n == 2 * 32 * 8 * 128 * 4096 * 2


def test_sampling_params_roundtrip():
    sp = SamplingParams(max_new_tokens=8, temperature=0.7, top_k=40,
                        stop_token_ids=(1, 2))
    assert SamplingParams.from_dict(sp.to_dict()) == sp


def test_job_status_enum():
    assert JobStatus("queued") is JobStatus.QUEUED
