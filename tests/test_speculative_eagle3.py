"""EAGLE-3 ingredients (VERDICT r3 #1b): multi-layer draft features +
on-policy distillation, unit-covered so the serving/distill paths can't
silently break between benchmark rounds."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from distributed_gpu_inference_tpu.models import llama
from distributed_gpu_inference_tpu.models.configs import get_model_config
from distributed_gpu_inference_tpu.runtime.engine import EngineConfig, TPUEngine
from distributed_gpu_inference_tpu.runtime.speculative import (
    SpeculativeConfig,
    SpeculativeDecoder,
    distill_draft_params,
    draft_apply,
    init_draft_params,
)
from distributed_gpu_inference_tpu.utils.data_structures import (
    InferenceRequest,
    SamplingParams,
)

CFG = get_model_config("llama3-tiny", dtype="float32")
FL = (1, 2, 3)      # low/mid/high of the 4-layer tiny model


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(0), jnp.float32)


def test_forward_chunk_collect_layers_shapes(params):
    b, s, bs, m = 2, 16, 16, 2
    kv = llama.init_kv_pools(CFG, 1 + b * m, bs, jnp.float32)
    toks = jnp.zeros((b, s), jnp.int32)
    pos = jnp.tile(jnp.arange(s, dtype=jnp.int32), (b, 1))
    tables = jnp.asarray(
        np.arange(1, 1 + b * m, dtype=np.int32).reshape(b, m))
    lens = jnp.full((b,), s, jnp.int32)
    out = llama.forward_chunk(CFG, params, toks, pos, kv, tables, lens,
                              block_size=bs, last_only=False,
                              collect_layers=FL)
    assert out.features.shape == (b, s, len(FL) * CFG.hidden_size)
    # the last collected layer IS the final hidden (post-layer == pre-norm)
    np.testing.assert_allclose(
        np.asarray(out.features[..., -CFG.hidden_size:]),
        np.asarray(out.hidden), rtol=1e-5, atol=1e-5,
    )


def test_draft_apply_w_feat_shape_dispatch():
    dp = init_draft_params(CFG, jax.random.PRNGKey(1),
                           num_feature_layers=len(FL))
    assert dp["w_feat"].shape == (len(FL) * CFG.hidden_size, CFG.hidden_size)
    h = CFG.hidden_size
    wide = jnp.ones((2, len(FL) * h), jnp.float32)
    narrow = jnp.ones((2, h), jnp.float32)
    emb = jnp.ones((2, h), jnp.float32)
    # both widths produce H-dim predictions (root vs deeper-level inputs)
    assert draft_apply(CFG, dp, wide, emb).shape == (2, h)
    assert draft_apply(CFG, dp, narrow, emb).shape == (2, h)


@pytest.mark.parametrize("kw", [
    dict(feature_layers=FL),
    dict(feature_layers=FL, on_policy=True),
    dict(on_policy=True),
])
def test_distill_variants_and_serving_bit_exact(params, kw):
    dp = distill_draft_params(CFG, params, jax.random.PRNGKey(2), steps=12,
                              num_batches=2, **kw)
    fl = kw.get("feature_layers")
    assert ("w_feat" in dp) == (fl is not None)
    spec = SpeculativeDecoder(
        CFG, params=params, draft_params=dp,
        spec_cfg=SpeculativeConfig(widths=(2, 2), adaptive=False,
                                   feature_layers=fl),
        max_batch_size=2, max_seq_len=128, block_size=16,
        prefill_buckets=(16,),
    )
    eng = TPUEngine(CFG, EngineConfig(
        max_batch_size=2, max_seq_len=128, block_size=16,
        prefill_buckets=(16,), dtype="float32",
        enable_prefix_cache=False), params=params)
    prompt = [(i * 29 + 3) % 500 for i in range(14)]
    req = lambda: InferenceRequest(  # noqa: E731
        prompt_token_ids=list(prompt),
        sampling=SamplingParams(max_new_tokens=10, temperature=0.0))
    got = spec.generate([req()])[0]
    want = eng.generate([req()])[0]
    # the verify construction guarantees bit-exactness regardless of
    # acceptance — this is the invariant a broken feature path would break
    assert got.token_ids == want.token_ids
    assert spec.get_stats()["drafted"] > 0


def test_custom_data_stream(params):
    calls = []

    def stream(key, b, s):
        calls.append((b, s))
        return jax.random.randint(key, (b, s), 0, CFG.vocab_size, jnp.int32)

    dp = distill_draft_params(CFG, params, jax.random.PRNGKey(3), steps=6,
                              num_batches=2, data_stream=stream)
    assert len(calls) == 2 and "w_fuse" in dp
