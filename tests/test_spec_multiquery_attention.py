"""Small-q multi-query paged attention (the speculative verify path,
q_len = K+1 per slot) vs the XLA oracle: causal masking within the chunk,
padded queries, sliding windows, int8 pools, and dispatch facts."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

def _pallas_tpu_usable() -> bool:
    """The kernel surface needs the TPU pallas memory-space API; older/
    newer jax builds that lack it fail at trace time even in interpret
    mode (the same build gap test_qmm_pallas.py hits). The off-chip space
    itself is shimmed (HBM falls back to ANY in the kernel module), so
    only VMEM is a hard requirement."""
    try:
        from jax.experimental.pallas import tpu as pltpu

        return hasattr(pltpu, "VMEM")
    except Exception:  # noqa: BLE001
        return False


# compile-heavy (jit/interpret kernels): excluded from the fast CI gate
pytestmark = pytest.mark.slow

needs_pallas = pytest.mark.skipif(
    not _pallas_tpu_usable(),
    reason="pallas TPU memory-space API unavailable in this jax build",
)

from distributed_gpu_inference_tpu.ops.attention import (
    paged_attention_xla,
    resolve_impl,
)


def _setup(b, s, kv_lens, nh, hkv, d, block, m, seed=0, pad_tail=0):
    """Random pools + a chain-shaped query chunk: row i's queries sit at
    positions kv_len - s .. kv_len - 1 (the verify window), with the
    chunk's KV already present in the pool — exactly the state the verify
    pass reads. ``pad_tail`` marks that many trailing queries per row as
    padding (position -1)."""
    key = jax.random.PRNGKey(seed)
    ks = jax.random.split(key, 3)
    num_blocks = 1 + b * m
    k_pool = jax.random.normal(ks[0], (num_blocks, hkv, block, d), jnp.float32)
    v_pool = jax.random.normal(ks[1], (num_blocks, hkv, block, d), jnp.float32)
    q = jax.random.normal(ks[2], (b, s, nh, d), jnp.float32)
    tables = np.zeros((b, m), np.int32)
    nxt = 1
    for i in range(b):
        tables[i] = np.arange(nxt, nxt + m)
        nxt += m
    lens = np.asarray(kv_lens, np.int32)
    positions = np.zeros((b, s), np.int32)
    for i in range(b):
        positions[i] = np.arange(lens[i] - s, lens[i])
    if pad_tail:
        positions[:, s - pad_tail:] = -1
    return (q, k_pool, v_pool, jnp.asarray(tables),
            jnp.asarray(positions), jnp.asarray(lens))


def _compare(args, block, window=None, atol=2e-5):
    from distributed_gpu_inference_tpu.ops.paged_attention_pallas import (
        paged_attention_pallas_multiquery,
    )

    q, k_pool, v_pool, tables, positions, lens = args
    want = paged_attention_xla(
        q, k_pool, v_pool, tables, positions, lens, block, window=window
    )
    got = paged_attention_pallas_multiquery(
        q, k_pool, v_pool, tables, positions, lens, block, window=window,
        interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=atol)


@needs_pallas
def test_verify_window_basic():
    _compare(_setup(2, 4, [9, 23], nh=4, hkv=2, d=64, block=16, m=4), 16)


@needs_pallas
def test_multi_group_context():
    # 300 tokens -> multiple page groups per query row
    _compare(_setup(2, 5, [300, 37], nh=8, hkv=4, d=64, block=16, m=20), 16)


@needs_pallas
def test_padded_tail_queries_are_zero():
    from distributed_gpu_inference_tpu.ops.paged_attention_pallas import (
        paged_attention_pallas_multiquery,
    )

    args = _setup(2, 5, [17, 40], nh=4, hkv=2, d=64, block=16, m=4,
                  pad_tail=2)
    _compare(args, 16)
    q, k_pool, v_pool, tables, positions, lens = args
    got = paged_attention_pallas_multiquery(
        q, k_pool, v_pool, tables, positions, lens, 16, interpret=True
    )
    assert np.all(np.asarray(got)[:, -2:] == 0.0)


@needs_pallas
@pytest.mark.parametrize("window", [4, 16])
def test_sliding_window(window):
    _compare(_setup(2, 3, [33, 50], nh=4, hkv=2, d=64, block=16, m=4), 16,
             window=window)


@needs_pallas
def test_int8_pool_parity():
    from distributed_gpu_inference_tpu.ops.attention import dequantize_kv
    from distributed_gpu_inference_tpu.ops.paged_attention_pallas import (
        paged_attention_pallas_multiquery,
        quantize_kv_pool,
    )

    q, k_pool, v_pool, tables, positions, lens = _setup(
        2, 4, [9, 40], nh=4, hkv=2, d=64, block=32, m=4
    )
    k_i8, k_s = quantize_kv_pool(k_pool)
    v_i8, v_s = quantize_kv_pool(v_pool)
    k_deq = dequantize_kv(k_i8, k_s[:, None, :, :])
    v_deq = dequantize_kv(v_i8, v_s[:, None, :, :])
    want = paged_attention_xla(
        q, k_deq, v_deq, tables, positions, lens, 32
    )
    got = paged_attention_pallas_multiquery(
        q, k_i8, v_i8, tables, positions, lens, 32, interpret=True,
        k_scale=k_s, v_scale=v_s,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)


def test_resolve_impl_small_q_dispatch():
    # q=1 decode stays on the fused kernel; EVERY multi-token span takes
    # the ragged kernel since round 6 — the old q_len <= 8 multi-query cap
    # (pages re-staged per query) is gone
    assert resolve_impl(1, 128, 1024, backend_is_tpu=True) == "pallas"
    for s in (2, 5, 8, 9, 16, 512):
        assert resolve_impl(s, 128, 1024, backend_is_tpu=True) == "ragged"
    # the existing guards still apply to multi-token spans
    assert resolve_impl(4, 64, 1024, backend_is_tpu=True) == "xla"
    assert resolve_impl(4, 128, 128, backend_is_tpu=True) == "xla"
    assert resolve_impl(4, 128, 1024, backend_is_tpu=False) == "xla"
