"""Real Redis L3 tier (runtime/redis_kv.py): RESP client against a
socket-level protocol fake, async writeback, fail-open, and the engine
spill chain running through the real client class (VERDICT r1 missing #2)."""

import socket
import threading
import time

import pytest

from distributed_gpu_inference_tpu.runtime.redis_kv import (
    RedisKVStore,
    RESPError,
    _encode_command,
    remote_store_from_url,
)


class FakeRedisServer:
    """Minimal RESP2 server: GET/SET(PX)/PING/AUTH/SELECT/DEL on a real
    socket — the client is exercised over the actual wire protocol."""

    def __init__(self):
        self.data = {}
        self.expiry = {}
        self.commands = []
        self.conns = []
        self.get_delay = 0.0     # stall before replying (deadline breach)
        self.dribble_s = 0.0     # split the GET reply, pause mid-send
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self._stop = False
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def close(self):
        self._stop = True
        # a thread blocked in accept() may still hand one last connection
        # to a client after close — clear the data so any straggler serve
        # answers a miss, which is what an outage must look like
        self.data.clear()
        self.expiry.clear()
        try:
            self.sock.close()
        except OSError:
            pass
        for c in self.conns:  # sever live connections too (outage sim)
            try:
                c.close()
            except OSError:
                pass

    def _serve(self):
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            self.conns.append(conn)
            threading.Thread(
                target=self._client, args=(conn,), daemon=True
            ).start()

    def _client(self, conn):
        buf = b""

        def read_line():
            nonlocal buf
            while b"\r\n" not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    raise ConnectionError
                buf += chunk
            line, buf2 = buf.split(b"\r\n", 1)
            return line, buf2

        try:
            while True:
                line, buf = read_line()
                assert line[:1] == b"*"
                n = int(line[1:])
                args = []
                for _ in range(n):
                    line, buf = read_line()
                    assert line[:1] == b"$"
                    ln = int(line[1:])
                    while len(buf) < ln + 2:
                        chunk = conn.recv(65536)
                        if not chunk:
                            raise ConnectionError
                        buf += chunk
                    args.append(buf[:ln])
                    buf = buf[ln + 2:]
                reply = self._dispatch([a for a in args])
                if self.dribble_s and args[0].upper() == b"GET" \
                        and len(reply) > 2:
                    # three chunks with sub-deadline gaps: each recv is
                    # fast, the aggregate GET is slow
                    third = max(len(reply) // 3, 1)
                    for i0 in range(0, len(reply), third):
                        conn.sendall(reply[i0:i0 + third])
                        time.sleep(self.dribble_s)
                else:
                    conn.sendall(reply)
        except (ConnectionError, OSError, AssertionError):
            conn.close()

    def _dispatch(self, args):
        cmd = args[0].upper()
        self.commands.append([cmd] + args[1:])
        if cmd == b"PING":
            return b"+PONG\r\n"
        if cmd in (b"AUTH", b"SELECT"):
            return b"+OK\r\n"
        if cmd == b"SET":
            key = args[1]
            self.data[key] = args[2]
            if len(args) >= 5 and args[3].upper() == b"PX":
                self.expiry[key] = time.monotonic() + int(args[4]) / 1000.0
            return b"+OK\r\n"
        if cmd == b"GET":
            if self.get_delay:
                time.sleep(self.get_delay)
            key = args[1]
            exp = self.expiry.get(key)
            if exp is not None and time.monotonic() > exp:
                self.data.pop(key, None)
                self.expiry.pop(key, None)
            val = self.data.get(key)
            if val is None:
                return b"$-1\r\n"
            return b"$%d\r\n%s\r\n" % (len(val), val)
        if cmd == b"DEL":
            existed = args[1] in self.data
            self.data.pop(args[1], None)
            return b":%d\r\n" % int(existed)
        return b"-ERR unknown command\r\n"


@pytest.fixture()
def server():
    s = FakeRedisServer()
    yield s
    s.close()


def _store(server, **kw):
    return RedisKVStore(host="127.0.0.1", port=server.port,
                        writeback_queue=32, **kw)


def test_put_get_roundtrip_over_the_wire(server):
    st = _store(server)
    try:
        assert st.ping()
        st.put("page-1", b"\x00\x01payload")
        assert st.flush()
        assert st.get("page-1") == b"\x00\x01payload"
        assert st.get("missing") is None
        assert st.stats["hits"] == 1
    finally:
        st.close()


def test_ttl_rides_the_server(server):
    st = _store(server, ttl_s=0.05)
    try:
        st.put("k", b"v")
        assert st.flush()
        # SET carried PX with the configured TTL
        sets = [c for c in server.commands if c[0] == b"SET"]
        assert sets and sets[0][3].upper() == b"PX"
        assert int(sets[0][4]) == 50
        time.sleep(0.08)
        assert st.get("k") is None  # expired server-side
    finally:
        st.close()


def test_writeback_is_async_and_bounded(server):
    st = _store(server)
    try:
        for i in range(100):   # queue bound 32: oldest writes drop
            st.put(f"k{i}", b"x" * 10)
        assert st.stats["puts"] == 100
        st.flush()
        assert st.stats["dropped"] > 0
        # the newest write always survives
        assert st.get("k99") == b"x" * 10
    finally:
        st.close()


def test_fail_open_when_server_down():
    st = RedisKVStore(host="127.0.0.1", port=1, reconnect_backoff_s=0.05,
                      writeback_queue=4)
    try:
        assert st.get("k") is None          # miss, no exception
        st.put("k", b"v")                   # swallowed, no exception
        assert not st.ping()
        assert st.stats["errors"] > 0
    finally:
        st.close()


def test_reconnects_after_outage(server):
    st = _store(server, reconnect_backoff_s=0.01)
    try:
        st.put("a", b"1")
        assert st.flush()
        # kill every live connection; the client must recover
        server.close()
        time.sleep(0.02)
        assert st.get("a") is None  # outage → fail-open miss
        s2 = FakeRedisServer()
        try:
            st2 = RedisKVStore(host="127.0.0.1", port=s2.port,
                               reconnect_backoff_s=0.01)
            try:
                st2.put("b", b"2")
                assert st2.flush()
                assert st2.get("b") == b"2"
            finally:
                st2.close()
        finally:
            s2.close()
    finally:
        st.close()


def test_slow_server_trips_latency_backoff(server):
    """A slow-but-responsive server must not stall the admission path for
    the full connect timeout per probe: the GET runs under probe_timeout_s
    and a breach fails open AND trips the reconnect backoff (ADVICE r2
    medium)."""
    st = _store(server, probe_timeout_s=0.05, timeout_s=2.0,
                reconnect_backoff_s=0.5)
    try:
        st.put("k", b"v")
        assert st.flush()
        server.get_delay = 0.3
        t0 = time.monotonic()
        assert st.get("k") is None           # deadline breach → miss
        assert time.monotonic() - t0 < 0.4   # bounded by probe, not 2 s
        assert st.stats["slow_trips"] == 1
        # inside the backoff window the socket isn't even touched
        n_cmds = len(server.commands)
        assert st.get("k") is None
        assert len(server.commands) == n_cmds
        # after the window, a healthy server serves hits again
        server.get_delay = 0.0
        time.sleep(0.55)
        assert st.get("k") == b"v"
    finally:
        st.close()


def test_slow_but_successful_reply_still_backs_off(server):
    """A reply that lands under the per-recv deadline on every chunk but
    over it in aggregate keeps the hit, yet trips the backoff — and the
    backoff must hold even though the connection stays alive."""
    st = _store(server, probe_timeout_s=0.08, timeout_s=2.0,
                reconnect_backoff_s=0.5)
    try:
        st.put("k", b"v")
        assert st.flush()
        server.dribble_s = 0.05   # 3 chunks, each gap < 0.08s deadline,
        # aggregate ~0.15s > probe_timeout_s
        assert st.get("k") == b"v"           # hit survives
        assert st.stats["slow_trips"] == 1
        # live connection + backoff window: next probe skips the socket
        n_cmds = len(server.commands)
        assert st.get("k") is None
        assert len(server.commands) == n_cmds
    finally:
        st.close()


def test_remote_store_from_url(server):
    st = remote_store_from_url(f"redis://127.0.0.1:{server.port}/2")
    try:
        assert isinstance(st, RedisKVStore)
        assert st.ping()
        # SELECT 2 was issued on connect
        assert [b"SELECT", b"2"] in server.commands
    finally:
        st.close()
    mem = remote_store_from_url("memory://")
    mem.put("k", b"v")
    assert mem.get("k") == b"v"
    assert remote_store_from_url(None) is None
    with pytest.raises(ValueError):
        remote_store_from_url("s3://bucket")


def test_engine_spill_chain_through_real_client(server):
    """The HBM→host→remote spill path serves a prefix through the REAL
    Redis client class (mirrors tests/test_kv_spill_tiers.py but with the
    wire-protocol store instead of the in-process dict)."""
    from distributed_gpu_inference_tpu.runtime.engine import (
        EngineConfig,
        TPUEngine,
    )
    from distributed_gpu_inference_tpu.utils.data_structures import (
        InferenceRequest,
        SamplingParams,
    )

    store = _store(server)
    try:
        eng = TPUEngine(
            "llama3-tiny",
            EngineConfig(
                max_batch_size=1, max_seq_len=64, block_size=16,
                prefill_buckets=(32,),
                num_blocks=8,            # tiny pool: forces eviction + spill
                spill_host_blocks=1,     # 1-block L2 → spills cascade to L3
                spill_remote_store=store,
                dtype="float32",
            ),
        )
        prompt_a = list(range(40, 72))   # 2 full blocks cacheable

        def run(p, n=8):
            return eng.generate([InferenceRequest(
                prompt_token_ids=list(p),
                sampling=SamplingParams(max_new_tokens=n, temperature=0.0),
            )])[0]

        r1 = run(prompt_a)
        # evict A's cached blocks with filler sequences → pages spill
        for i in range(4):
            run([(i * 3 + j) % 500 for j in [7, 9] * 16])
        store.flush()
        assert store.stats["puts"] > 0, "eviction must spill to redis"
        r2 = run(prompt_a)
        store.flush()
        assert r2.token_ids == r1.token_ids
        # the second admission restored at least one page from the L3 tier
        assert store.stats["hits"] > 0
        assert eng.manager.stats.l3_hits > 0
    finally:
        store.close()
