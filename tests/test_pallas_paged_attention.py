"""Pallas paged-attention decode kernel vs the XLA oracle (interpret mode).

The kernel (ops/paged_attention_pallas.py) must match paged_attention_xla
bit-close on every masking case: GQA, partial pages, multi-group contexts,
sliding windows, inactive slots."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# compile-heavy (jit/scan graphs): excluded from the fast CI gate
pytestmark = pytest.mark.slow

from distributed_gpu_inference_tpu.ops.attention import paged_attention_xla
from distributed_gpu_inference_tpu.ops.paged_attention_pallas import (
    paged_attention_pallas,
)


def _setup(b, kv_lens, nh, hkv, d, block, m, seed=0):
    """Random pools with each sequence's pages filled up to its kv_len."""
    key = jax.random.PRNGKey(seed)
    ks = jax.random.split(key, 3)
    num_blocks = 1 + b * m
    k_pool = jax.random.normal(ks[0], (num_blocks, hkv, block, d), jnp.float32)
    v_pool = jax.random.normal(ks[1], (num_blocks, hkv, block, d), jnp.float32)
    q = jax.random.normal(ks[2], (b, 1, nh, d), jnp.float32)
    tables = np.zeros((b, m), np.int32)
    nxt = 1
    for i in range(b):
        tables[i] = np.arange(nxt, nxt + m)
        nxt += m
    lens = np.asarray(kv_lens, np.int32)
    positions = (lens - 1)[:, None].astype(np.int32)
    return (q, k_pool, v_pool, jnp.asarray(tables),
            jnp.asarray(positions), jnp.asarray(lens))


def _compare(args, block, window=None, atol=2e-5):
    q, k_pool, v_pool, tables, positions, lens = args
    want = paged_attention_xla(
        q, k_pool, v_pool, tables, positions, lens, block, window=window
    )
    got = paged_attention_pallas(
        q, k_pool, v_pool, tables, positions, lens, block, window=window,
        interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=atol)


def test_basic_decode_partial_page():
    _compare(_setup(2, [9, 23], nh=4, hkv=2, d=64, block=16, m=4), 16)


def test_multi_group_long_context():
    # 300 tokens → 19 pages → 3 page groups (8 pages each)
    _compare(_setup(2, [300, 17], nh=8, hkv=4, d=64, block=16, m=20), 16)


def test_group_boundary_exact():
    # kv_len exactly a group multiple (8 pages * 16 = 128)
    _compare(_setup(1, [128], nh=4, hkv=2, d=64, block=16, m=8), 16)


def test_single_token_context():
    _compare(_setup(1, [1], nh=4, hkv=4, d=64, block=16, m=2), 16)


def test_mqa_single_kv_head():
    _compare(_setup(2, [40, 7], nh=8, hkv=1, d=64, block=16, m=4), 16)


def test_inactive_slot_zero_output():
    args = _setup(3, [12, 0, 5], nh=4, hkv=2, d=64, block=16, m=2)
    q, k_pool, v_pool, tables, positions, lens = args
    got = paged_attention_pallas(
        q, k_pool, v_pool, tables, positions, lens, 16, interpret=True
    )
    assert np.all(np.asarray(got)[1] == 0.0)
    _compare(args, 16)


@pytest.mark.parametrize("window", [4, 16, 100])
def test_sliding_window(window):
    _compare(_setup(2, [150, 30], nh=4, hkv=2, d=64, block=16, m=10), 16,
             window=window)


def test_window_skips_leading_groups():
    """Window smaller than one group: dead leading groups are skipped but
    output still matches the oracle."""
    _compare(_setup(1, [290], nh=4, hkv=2, d=64, block=16, m=20), 16,
             window=32)


def test_head_dim_128():
    _compare(_setup(1, [21], nh=4, hkv=2, d=128, block=16, m=2), 16)


def test_bfloat16_pools():
    q, k_pool, v_pool, tables, positions, lens = _setup(
        2, [33, 60], nh=4, hkv=2, d=64, block=16, m=4
    )
    q = q.astype(jnp.bfloat16)
    k_pool = k_pool.astype(jnp.bfloat16)
    v_pool = v_pool.astype(jnp.bfloat16)
    want = paged_attention_xla(q, k_pool, v_pool, tables, positions, lens, 16)
    got = paged_attention_pallas(q, k_pool, v_pool, tables, positions, lens,
                                 16, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_rejects_prefill_shapes():
    q = jnp.zeros((1, 4, 4, 64), jnp.float32)
    k = jnp.zeros((4, 2, 16, 64), jnp.float32)
    with pytest.raises(ValueError, match="decode"):
        paged_attention_pallas(
            q, k, k, jnp.zeros((1, 2), jnp.int32),
            jnp.zeros((1, 4), jnp.int32), jnp.zeros((1,), jnp.int32),
            16, interpret=True,
        )


# ---------------------------------------------------------------------------
# Fused write+attention kernel (round 2): the decode step writes this step's
# K/V rows into their page slots inside the kernel (pools aliased in place).
# Reference = XLA scatter (models.llama._write_kv_pages) + paged_attention_xla
# over the same inputs.
# ---------------------------------------------------------------------------


def _mk_fused_case(seed, b, hkv, qpk, d, bs, m, n_layers, nblocks, lens):
    import numpy as np

    rng = np.random.default_rng(seed)
    nh = hkv * qpk
    q = rng.standard_normal((b, 1, nh, d), dtype=np.float32)
    new_k = rng.standard_normal((b, 1, hkv, d), dtype=np.float32)
    new_v = rng.standard_normal((b, 1, hkv, d), dtype=np.float32)
    k_pool = rng.standard_normal(
        (n_layers, nblocks, hkv, bs, d), dtype=np.float32
    )
    v_pool = rng.standard_normal(
        (n_layers, nblocks, hkv, bs, d), dtype=np.float32
    )
    tables = np.zeros((b, m), np.int32)
    for i in range(b):
        tables[i] = 1 + (np.arange(m) * b + i) % (nblocks - 1)
    lens = np.asarray(lens, np.int32)
    positions = (lens - 1)[:, None].astype(np.int32)  # write pos = len - 1
    return q, new_k, new_v, k_pool, v_pool, tables, positions, lens


@pytest.mark.parametrize("lens", [
    [33, 5, 64, 1],          # mixed short
    [0, 40, 0, 17],          # inactive rows (no write, zero out)
    [64, 64, 64, 64],        # full tables
])
def test_fused_write_attention_parity(lens):
    import numpy as np

    from distributed_gpu_inference_tpu.models.llama import _write_kv_pages
    from distributed_gpu_inference_tpu.ops.attention import paged_attention_xla
    from distributed_gpu_inference_tpu.ops.paged_attention_pallas import (
        paged_decode_attention_fused,
    )

    b, hkv, qpk, d, bs, m, L, nblocks = 4, 2, 3, 128, 16, 4, 3, 40
    layer = 1
    q, new_k, new_v, k_pool, v_pool, tables, positions, lens_a = \
        _mk_fused_case(0, b, hkv, qpk, d, bs, m, L, nblocks, lens)

    out, k2, v2 = paged_decode_attention_fused(
        jnp.asarray(q), jnp.asarray(new_k), jnp.asarray(new_v),
        jnp.asarray(k_pool), jnp.asarray(v_pool), jnp.int32(layer),
        jnp.asarray(tables), jnp.asarray(positions), jnp.asarray(lens_a),
        block_size=bs, interpret=True,
    )

    # reference: scatter the rows into the layer slice, then XLA attention
    ref_k = _write_kv_pages(
        jnp.asarray(k_pool[layer]), jnp.asarray(new_k),
        jnp.asarray(tables), jnp.asarray(positions), bs,
    )
    ref_v = _write_kv_pages(
        jnp.asarray(v_pool[layer]), jnp.asarray(new_v),
        jnp.asarray(tables), jnp.asarray(positions), bs,
    )
    ref_out = paged_attention_xla(
        jnp.asarray(q), ref_k, ref_v, jnp.asarray(tables),
        jnp.asarray(positions), jnp.asarray(lens_a), block_size=bs,
    )

    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref_out), rtol=2e-2, atol=2e-2
    )
    # pool side effects: written layer matches the scatter reference bit-for
    # bit on touched pages; other layers untouched
    np.testing.assert_allclose(np.asarray(k2[layer]), np.asarray(ref_k),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(v2[layer]), np.asarray(ref_v),
                               rtol=1e-6, atol=1e-6)
    for other in (0, 2):
        np.testing.assert_array_equal(
            np.asarray(k2[other]), k_pool[other]
        )


def test_fused_write_respects_window():
    import numpy as np

    from distributed_gpu_inference_tpu.models.llama import _write_kv_pages
    from distributed_gpu_inference_tpu.ops.attention import paged_attention_xla
    from distributed_gpu_inference_tpu.ops.paged_attention_pallas import (
        paged_decode_attention_fused,
    )

    b, hkv, qpk, d, bs, m, L, nblocks = 2, 2, 2, 128, 16, 6, 1, 30
    q, new_k, new_v, k_pool, v_pool, tables, positions, lens_a = \
        _mk_fused_case(3, b, hkv, qpk, d, bs, m, L, nblocks, [80, 41])

    out, k2, v2 = paged_decode_attention_fused(
        jnp.asarray(q), jnp.asarray(new_k), jnp.asarray(new_v),
        jnp.asarray(k_pool), jnp.asarray(v_pool), jnp.int32(0),
        jnp.asarray(tables), jnp.asarray(positions), jnp.asarray(lens_a),
        block_size=bs, window=32, interpret=True,
    )
    ref_k = _write_kv_pages(
        jnp.asarray(k_pool[0]), jnp.asarray(new_k),
        jnp.asarray(tables), jnp.asarray(positions), bs,
    )
    ref_v = _write_kv_pages(
        jnp.asarray(v_pool[0]), jnp.asarray(new_v),
        jnp.asarray(tables), jnp.asarray(positions), bs,
    )
    ref_out = paged_attention_xla(
        jnp.asarray(q), ref_k, ref_v, jnp.asarray(tables),
        jnp.asarray(positions), jnp.asarray(lens_a), block_size=bs,
        window=32,
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref_out), rtol=2e-2, atol=2e-2
    )


def test_fp8_pools_parity():
    """fp8 KV pools (kv_cache_dtype="fp8"): kernel upcasts pages to bf16 in
    VMEM; parity vs the XLA path over the SAME fp8-rounded values."""
    q, k_pool, v_pool, tables, positions, lens = _setup(
        2, [33, 60], nh=4, hkv=2, d=64, block=16, m=4
    )
    q = q.astype(jnp.bfloat16)
    k_pool = k_pool.astype(jnp.float8_e4m3fn)
    v_pool = v_pool.astype(jnp.float8_e4m3fn)
    want = paged_attention_xla(q, k_pool, v_pool, tables, positions, lens, 16)
    got = paged_attention_pallas(q, k_pool, v_pool, tables, positions, lens,
                                 16, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=3e-2, atol=3e-2,
    )


def test_fused_write_fp8_pools():
    """Fused write+attention with fp8 pools: new rows are cast to fp8 before
    the kernel (models/llama._layer_step does this); the written layer must
    match the XLA scatter of the same fp8 rows and attention must agree."""
    import numpy as np

    from distributed_gpu_inference_tpu.models.llama import _write_kv_pages
    from distributed_gpu_inference_tpu.ops.attention import paged_attention_xla
    from distributed_gpu_inference_tpu.ops.paged_attention_pallas import (
        paged_decode_attention_fused,
    )

    b, hkv, qpk, d, bs, m, L, nblocks = 2, 2, 2, 128, 16, 4, 2, 20
    q, new_k, new_v, k_pool, v_pool, tables, positions, lens_a = \
        _mk_fused_case(7, b, hkv, qpk, d, bs, m, L, nblocks, [33, 5])
    fp8 = jnp.float8_e4m3fn
    k_pool8 = jnp.asarray(k_pool).astype(fp8)
    v_pool8 = jnp.asarray(v_pool).astype(fp8)
    nk8 = jnp.asarray(new_k).astype(fp8)
    nv8 = jnp.asarray(new_v).astype(fp8)

    out, k2, v2 = paged_decode_attention_fused(
        jnp.asarray(q, jnp.bfloat16), nk8, nv8,
        k_pool8, v_pool8, jnp.int32(1),
        jnp.asarray(tables), jnp.asarray(positions), jnp.asarray(lens_a),
        block_size=bs, interpret=True,
    )
    ref_k = _write_kv_pages(
        k_pool8[1], nk8, jnp.asarray(tables), jnp.asarray(positions), bs
    )
    ref_v = _write_kv_pages(
        v_pool8[1], nv8, jnp.asarray(tables), jnp.asarray(positions), bs
    )
    ref_out = paged_attention_xla(
        jnp.asarray(q, jnp.bfloat16), ref_k, ref_v, jnp.asarray(tables),
        jnp.asarray(positions), jnp.asarray(lens_a), block_size=bs,
    )
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref_out, np.float32),
        rtol=3e-2, atol=3e-2,
    )
    np.testing.assert_array_equal(
        np.asarray(k2[1], np.float32), np.asarray(ref_k, np.float32)
    )
    np.testing.assert_array_equal(
        np.asarray(v2[1], np.float32), np.asarray(ref_v, np.float32)
    )


# ---------------------------------------------------------------------------
# int8 KV pool: per-token scales, scores/PV rescale in-kernel (VERDICT r3 #4)
# ---------------------------------------------------------------------------


from distributed_gpu_inference_tpu.ops.paged_attention_pallas import (  # noqa: E402
    quantize_kv_pool as _quantize_pool,
)


def _compare_int8(args, block, window=None):
    """Oracle = XLA attention over the DEQUANTIZED pool: the kernel must
    reproduce the quantized-pool math, not hide extra error beyond it."""
    q, k_pool, v_pool, tables, positions, lens = args
    k_i8, ks = _quantize_pool(k_pool)
    v_i8, vs = _quantize_pool(v_pool)
    k_deq = k_i8.astype(jnp.float32) * ks.astype(jnp.float32)[:, None, :, :]
    v_deq = v_i8.astype(jnp.float32) * vs.astype(jnp.float32)[:, None, :, :]
    want = paged_attention_xla(
        q, k_deq, v_deq, tables, positions, lens, block, window=window
    )
    got = paged_attention_pallas(
        q, k_i8, v_i8, tables, positions, lens, block, window=window,
        interpret=True, k_scale=ks, v_scale=vs,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)


def test_int8_pool_basic():
    _compare_int8(_setup(2, [9, 23], nh=4, hkv=2, d=64, block=32, m=4), 32)


def test_int8_pool_multi_group():
    _compare_int8(_setup(2, [300, 17], nh=8, hkv=4, d=64, block=32, m=12), 32)


def test_int8_pool_window():
    _compare_int8(_setup(2, [200, 64], nh=4, hkv=2, d=64, block=32, m=8), 32,
                  window=48)


def test_int8_pool_inactive_rows():
    args = _setup(3, [40, 1, 16], nh=4, hkv=2, d=64, block=32, m=4)
    q, k_pool, v_pool, tables, positions, lens = args
    positions = positions.at[1, 0].set(-1)   # row 1 inactive
    _compare_int8((q, k_pool, v_pool, tables, positions, lens), 32)


def test_int8_quantization_error_vs_full_precision_bounded():
    """Sanity: int8-KV output stays within ~1% of the FULL-precision
    attention (per-token amax scaling) — the capacity knob must not wreck
    quality."""
    args = _setup(2, [100, 50], nh=4, hkv=2, d=64, block=32, m=4)
    q, k_pool, v_pool, tables, positions, lens = args
    full = paged_attention_xla(
        q, k_pool, v_pool, tables, positions, lens, 32
    )
    k_i8, ks = _quantize_pool(k_pool)
    v_i8, vs = _quantize_pool(v_pool)
    got = paged_attention_pallas(
        q, k_i8, v_i8, tables, positions, lens, 32,
        interpret=True, k_scale=ks, v_scale=vs,
    )
    err = float(jnp.max(jnp.abs(got - full)))
    ref = float(jnp.max(jnp.abs(full)))
    assert err < 0.02 * max(ref, 1.0), f"int8 KV error too large: {err}"


def test_int8_fused_write_quantizes_in_kernel():
    """Fused decode on int8 pools: the kernel quantizes this step's K/V
    rows in place (per-token scale, same contract as quantize_kv_pool) and
    its own attention sees them. Oracle: quantize the row on the host with
    the same contract, place it in the pool, run the read-only path."""
    from distributed_gpu_inference_tpu.ops.paged_attention_pallas import (
        paged_decode_attention_fused,
    )

    b, nh, hkv, d, block, m = 2, 4, 2, 64, 32, 3
    args = _setup(b, [33, 9], nh, hkv, d, block, m)
    q, k_pool, v_pool, tables, positions, lens = args
    # context BEFORE this step's token
    prev_lens = jnp.asarray([32, 8], jnp.int32)
    new_lens = prev_lens + 1
    wpos = prev_lens[:, None]        # write at the next slot

    key = jax.random.PRNGKey(9)
    new_k = jax.random.normal(key, (b, 1, hkv, d), jnp.float32)
    new_v = jax.random.normal(jax.random.fold_in(key, 1), (b, 1, hkv, d),
                              jnp.float32)

    k_i8, ks = _quantize_pool(k_pool)
    v_i8, vs = _quantize_pool(v_pool)

    out, k2, v2, ks2, vs2 = paged_decode_attention_fused(
        q, new_k, new_v, k_i8[None], v_i8[None], jnp.int32(0),
        tables, wpos, new_lens, block, interpret=True,
        k_scale=ks[None], v_scale=vs[None],
    )

    # oracle: quantize the new rows host-side with the same contract and
    # rebuild the dequantized pool the kernel should have attended over
    def host_write(pool_i8, scales, new_rows):
        pool_i8, scales = np.asarray(pool_i8).copy(), \
            np.asarray(scales, np.float32).copy()
        for r in range(b):
            p = int(np.asarray(tables)[r, int(prev_lens[r]) // block])
            slot = int(prev_lens[r]) % block
            row = np.asarray(new_rows[r, 0], np.float32)      # [Hkv, D]
            s = np.float32(max(np.abs(row).max(), 1e-6) / 127.0)
            s = np.float32(jnp.bfloat16(s))                   # stored bf16
            pool_i8[p, :, slot, :] = np.clip(
                np.round(row / s), -127, 127
            ).astype(np.int8)
            scales[p, slot, :] = s
        return pool_i8, scales

    k_ref, ks_ref = host_write(k_i8, ks, new_k)
    v_ref, vs_ref = host_write(v_i8, vs, new_v)
    np.testing.assert_array_equal(np.asarray(k2[0]), k_ref)
    np.testing.assert_array_equal(np.asarray(v2[0]), v_ref)
    np.testing.assert_allclose(np.asarray(ks2[0], np.float32), ks_ref,
                               rtol=1e-2, atol=1e-4)

    k_deq = k_ref.astype(np.float32) * np.asarray(ks_ref)[:, None, :, :]
    v_deq = v_ref.astype(np.float32) * np.asarray(vs_ref)[:, None, :, :]
    want = paged_attention_xla(
        q, jnp.asarray(k_deq), jnp.asarray(v_deq), tables,
        prev_lens[:, None], new_lens, block
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-2, atol=2e-2)
