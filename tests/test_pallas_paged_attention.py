"""Pallas paged-attention decode kernel vs the XLA oracle (interpret mode).

The kernel (ops/paged_attention_pallas.py) must match paged_attention_xla
bit-close on every masking case: GQA, partial pages, multi-group contexts,
sliding windows, inactive slots."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_gpu_inference_tpu.ops.attention import paged_attention_xla
from distributed_gpu_inference_tpu.ops.paged_attention_pallas import (
    paged_attention_pallas,
)


def _setup(b, kv_lens, nh, hkv, d, block, m, seed=0):
    """Random pools with each sequence's pages filled up to its kv_len."""
    key = jax.random.PRNGKey(seed)
    ks = jax.random.split(key, 3)
    num_blocks = 1 + b * m
    k_pool = jax.random.normal(ks[0], (num_blocks, hkv, block, d), jnp.float32)
    v_pool = jax.random.normal(ks[1], (num_blocks, hkv, block, d), jnp.float32)
    q = jax.random.normal(ks[2], (b, 1, nh, d), jnp.float32)
    tables = np.zeros((b, m), np.int32)
    nxt = 1
    for i in range(b):
        tables[i] = np.arange(nxt, nxt + m)
        nxt += m
    lens = np.asarray(kv_lens, np.int32)
    positions = (lens - 1)[:, None].astype(np.int32)
    return (q, k_pool, v_pool, jnp.asarray(tables),
            jnp.asarray(positions), jnp.asarray(lens))


def _compare(args, block, window=None, atol=2e-5):
    q, k_pool, v_pool, tables, positions, lens = args
    want = paged_attention_xla(
        q, k_pool, v_pool, tables, positions, lens, block, window=window
    )
    got = paged_attention_pallas(
        q, k_pool, v_pool, tables, positions, lens, block, window=window,
        interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=atol)


def test_basic_decode_partial_page():
    _compare(_setup(2, [9, 23], nh=4, hkv=2, d=64, block=16, m=4), 16)


def test_multi_group_long_context():
    # 300 tokens → 19 pages → 3 page groups (8 pages each)
    _compare(_setup(2, [300, 17], nh=8, hkv=4, d=64, block=16, m=20), 16)


def test_group_boundary_exact():
    # kv_len exactly a group multiple (8 pages * 16 = 128)
    _compare(_setup(1, [128], nh=4, hkv=2, d=64, block=16, m=8), 16)


def test_single_token_context():
    _compare(_setup(1, [1], nh=4, hkv=4, d=64, block=16, m=2), 16)


def test_mqa_single_kv_head():
    _compare(_setup(2, [40, 7], nh=8, hkv=1, d=64, block=16, m=4), 16)


def test_inactive_slot_zero_output():
    args = _setup(3, [12, 0, 5], nh=4, hkv=2, d=64, block=16, m=2)
    q, k_pool, v_pool, tables, positions, lens = args
    got = paged_attention_pallas(
        q, k_pool, v_pool, tables, positions, lens, 16, interpret=True
    )
    assert np.all(np.asarray(got)[1] == 0.0)
    _compare(args, 16)


@pytest.mark.parametrize("window", [4, 16, 100])
def test_sliding_window(window):
    _compare(_setup(2, [150, 30], nh=4, hkv=2, d=64, block=16, m=10), 16,
             window=window)


def test_window_skips_leading_groups():
    """Window smaller than one group: dead leading groups are skipped but
    output still matches the oracle."""
    _compare(_setup(1, [290], nh=4, hkv=2, d=64, block=16, m=20), 16,
             window=32)


def test_head_dim_128():
    _compare(_setup(1, [21], nh=4, hkv=2, d=128, block=16, m=2), 16)


def test_bfloat16_pools():
    q, k_pool, v_pool, tables, positions, lens = _setup(
        2, [33, 60], nh=4, hkv=2, d=64, block=16, m=4
    )
    q = q.astype(jnp.bfloat16)
    k_pool = k_pool.astype(jnp.bfloat16)
    v_pool = v_pool.astype(jnp.bfloat16)
    want = paged_attention_xla(q, k_pool, v_pool, tables, positions, lens, 16)
    got = paged_attention_pallas(q, k_pool, v_pool, tables, positions, lens,
                                 16, interpret=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_rejects_prefill_shapes():
    q = jnp.zeros((1, 4, 4, 64), jnp.float32)
    k = jnp.zeros((4, 2, 16, 64), jnp.float32)
    with pytest.raises(ValueError, match="decode"):
        paged_attention_pallas(
            q, k, k, jnp.zeros((1, 2), jnp.int32),
            jnp.zeros((1, 4), jnp.int32), jnp.zeros((1,), jnp.int32),
            16, interpret=True,
        )
