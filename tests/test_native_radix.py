"""Native C++ radix index: build, exact parity with the Python index.

The native component must be a DROP-IN for ``RadixPrefixIndex`` — same
results on identical operation sequences, including interior-eviction
refusal. Fuzzed against the Python implementation.
"""

import random

import pytest

from distributed_gpu_inference_tpu.native import native_available
from distributed_gpu_inference_tpu.runtime.kv_cache import (
    RadixPrefixIndex,
    make_radix_index,
)

needs_native = pytest.mark.skipif(
    not native_available(), reason="native toolchain unavailable"
)


def test_factory_returns_some_index():
    idx = make_radix_index(16)
    assert idx.block_size == 16
    assert idx.match_prefix([1] * 16) == []


def test_factory_fallback_forced(monkeypatch):
    idx = make_radix_index(16, prefer_native=False)
    assert isinstance(idx, RadixPrefixIndex)


@needs_native
def test_native_builds_and_loads():
    from distributed_gpu_inference_tpu.native.radix import (
        NativeRadixPrefixIndex,
    )

    idx = NativeRadixPrefixIndex(4)
    assert len(idx) == 0
    assert idx.insert([1, 2, 3, 4, 5, 6, 7, 8], [10, 11]) == 2
    assert len(idx) == 2
    assert idx.match_prefix([1, 2, 3, 4, 5, 6, 7, 8, 9]) == [10, 11]
    assert idx.match_prefix([1, 2, 3, 4, 9, 9, 9, 9]) == [10]
    assert idx.match_prefix([9, 9, 9, 9]) == []
    assert idx.contains_block(10) and idx.contains_block(11)
    assert idx.is_leaf(11) and not idx.is_leaf(10)
    with pytest.raises(ValueError, match="interior"):
        idx.remove_block(10)
    idx.remove_block(11)
    assert not idx.contains_block(11)
    assert idx.is_leaf(10)
    idx.remove_block(99)  # absent: no-op


@needs_native
def test_native_partial_blocks_never_shared():
    from distributed_gpu_inference_tpu.native.radix import (
        NativeRadixPrefixIndex,
    )

    idx = NativeRadixPrefixIndex(4)
    # 6 tokens = 1 full block; the partial tail is not indexed
    assert idx.insert([1, 2, 3, 4, 5, 6], [20, 21]) == 1
    assert idx.match_prefix([1, 2, 3, 4, 5, 6]) == [20]


@needs_native
def test_native_matches_python_fuzz():
    """Identical op sequences must produce identical results."""
    from distributed_gpu_inference_tpu.native.radix import (
        NativeRadixPrefixIndex,
    )

    rng = random.Random(7)
    bs = 4
    py = RadixPrefixIndex(bs)
    cc = NativeRadixPrefixIndex(bs)
    next_block = [1]
    inserted = []

    for step in range(400):
        op = rng.random()
        n_tok = rng.randrange(0, 8 * bs)
        toks = [rng.randrange(0, 9) for _ in range(n_tok)]
        if op < 0.45:
            m_py = py.match_prefix(toks)
            m_cc = cc.match_prefix(toks)
            assert m_py == m_cc, f"step {step}: match diverged"
        elif op < 0.8:
            n_full = n_tok // bs
            blocks = [next_block[0] + i for i in range(n_full)]
            next_block[0] += n_full
            a_py = py.insert(toks, blocks)
            a_cc = cc.insert(toks, blocks)
            assert a_py == a_cc, f"step {step}: insert count diverged"
            inserted.extend(blocks)
        elif inserted:
            bid = rng.choice(inserted)
            assert py.contains_block(bid) == cc.contains_block(bid)
            assert py.is_leaf(bid) == cc.is_leaf(bid)
            err_py = err_cc = False
            try:
                py.remove_block(bid)
            except ValueError:
                err_py = True
            try:
                cc.remove_block(bid)
            except ValueError:
                err_cc = True
            assert err_py == err_cc, f"step {step}: remove behavior diverged"
            assert py.contains_block(bid) == cc.contains_block(bid)
    assert len(py) == len(cc)


@needs_native
def test_manager_works_with_native_index():
    """PagedKVCacheManager's full sequence lifecycle over the C++ index."""
    from distributed_gpu_inference_tpu.runtime.kv_cache import (
        PagedKVCacheManager,
    )
    from distributed_gpu_inference_tpu.native.radix import (
        NativeRadixPrefixIndex,
    )

    mgr = PagedKVCacheManager(32, block_size=4)
    assert isinstance(mgr.radix, NativeRadixPrefixIndex)
    blocks, cached = mgr.allocate_sequence("a", list(range(10)))
    assert cached == 0 and len(blocks) == 3
    mgr.free_sequence("a", cache=True)
    # same prefix → cache hit on the full blocks
    blocks2, cached2 = mgr.allocate_sequence("b", list(range(10)))
    assert cached2 == 8
    assert blocks2[:2] == blocks[:2]
    mgr.free_sequence("b", cache=False)
