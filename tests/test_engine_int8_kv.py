"""Engine-level int8 KV cache (kv_cache_dtype="int8", VERDICT r3 #4).

Pools store int8 pages + per-(page, token) scale pools; writes quantize in
the layer step (XLA path) or in the fused Pallas kernel (TPU decode); reads
dequantize context-sized. int8 KV is LOSSY — greedy outputs are compared
prefix-wise (near-ties may flip late), while structure (scale pools, CoW,
fences) is exact."""

import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow

from distributed_gpu_inference_tpu.models.configs import get_model_config
from distributed_gpu_inference_tpu.runtime.engine import EngineConfig, TPUEngine
from distributed_gpu_inference_tpu.utils.data_structures import (
    InferenceRequest,
    SamplingParams,
)

CFG = get_model_config("llama3-tiny", dtype="float32")


def _kw(**over):
    base = dict(max_batch_size=2, max_seq_len=128, block_size=32,
                prefill_buckets=(32,), dtype="float32", multi_step=4,
                enable_prefix_cache=False)
    base.update(over)
    return base


def _req(prompt, n=12):
    return InferenceRequest(
        prompt_token_ids=list(prompt),
        sampling=SamplingParams(max_new_tokens=n, temperature=0.0))


@pytest.fixture(scope="module")
def params():
    return TPUEngine(CFG, EngineConfig(**_kw()), seed=0).params


def test_int8_engine_builds_scale_pools(params):
    eng = TPUEngine(CFG, EngineConfig(kv_cache_dtype="int8", **_kw()),
                    params=params)
    assert eng.kv["k"].dtype == jnp.int8
    assert eng.kv["k_scale"].dtype == jnp.bfloat16
    L, N, _, bk, d = eng.kv["k"].shape
    assert eng.kv["k_scale"].shape == (L, N, bk, d)


def test_int8_engine_greedy_close_to_bf16(params):
    ref = TPUEngine(CFG, EngineConfig(**_kw()), params=params)
    q8 = TPUEngine(CFG, EngineConfig(kv_cache_dtype="int8", **_kw()),
                   params=params)
    prompt = [(i * 29 + 3) % 500 for i in range(20)]
    want = ref.generate([_req(prompt)], use_multi_step=True)[0]
    got = q8.generate([_req(prompt)], use_multi_step=True)[0]
    assert len(got.token_ids) == len(want.token_ids)
    # the first several greedy steps must agree (per-token amax scaling is
    # ~0.5% relative error; only near-ties can flip, and not immediately)
    assert got.token_ids[:6] == want.token_ids[:6], (
        got.token_ids, want.token_ids)


def test_int8_prefix_cache_cow(params):
    """Prefix hits + CoW on int8 pools: scale pages must travel with their
    data pages through the copy path."""
    q8 = TPUEngine(CFG, EngineConfig(kv_cache_dtype="int8",
                                     **_kw(enable_prefix_cache=True)),
                   params=params)
    prefix = [(i * 13 + 1) % 500 for i in range(40)]
    q8.generate([_req(prefix, 2)], use_multi_step=True)
    full = prefix + [7, 8, 9, 10]
    r = q8.generate([_req(full, 8)], use_multi_step=True)[0]
    assert r.cached_tokens >= 32
    assert len(r.token_ids) == 8


def test_int8_handoff_dtype_mismatch(params):
    # an int8 handoff must not land in a bf16 engine (raw int8 codes would
    # be read as real values) — and vice versa. (The round-4 mesh and
    # spill fences are gone: tests/test_engine_int8_mesh.py and the int8
    # cases in tests/test_kv_spill_tiers.py cover those compositions.)
    from distributed_gpu_inference_tpu.runtime.kv_handoff import (
        adopt_kv,
        deserialize_handoff,
        export_slot_kv,
        serialize_handoff,
    )

    q8 = TPUEngine(CFG, EngineConfig(kv_cache_dtype="int8", **_kw()),
                   params=params)
    slot = q8.submit(_req([1, 2, 3, 4], 4))
    h = deserialize_handoff(serialize_handoff(export_slot_kv(q8, slot)))
    assert h.scale_pages is not None
    fp = TPUEngine(CFG, EngineConfig(**_kw()), params=params)
    with pytest.raises(ValueError, match="kv_cache_dtype mismatch"):
        adopt_kv(fp, h)


def test_int8_oneshot_wire_handoff_bit_exact(params):
    """int8 donor → wire → int8 recipient: pages AND scales cross, so the
    continuation is bit-exact (no requantization anywhere)."""
    from distributed_gpu_inference_tpu.runtime.kv_handoff import (
        adopt_kv,
        deserialize_handoff,
        export_slot_kv,
        serialize_handoff,
    )

    donor = TPUEngine(CFG, EngineConfig(kv_cache_dtype="int8", **_kw()),
                      params=params)
    recv = TPUEngine(CFG, EngineConfig(kv_cache_dtype="int8", **_kw()),
                     params=params)
    oracle = TPUEngine(CFG, EngineConfig(kv_cache_dtype="int8", **_kw()),
                       params=params)
    prompt = [(i * 31 + 7) % 500 for i in range(24)]
    want = oracle.generate([_req(prompt, 12)], use_multi_step=True)[0]

    slot = donor.submit(_req(prompt, 12))
    for _ in range(3):
        donor.decode_step()
    wire = serialize_handoff(export_slot_kv(donor, slot))
    donor.finish_slot(slot, cache=False)
    dslot = adopt_kv(recv, deserialize_handoff(wire))
    while recv.slots[dslot] is not None and \
            recv.slots[dslot].finish_reason is None:
        recv.decode_step()
    got = recv.finish_slot(dslot)
    assert got.token_ids == want.token_ids, (got.token_ids, want.token_ids)


def test_int8_streamed_handoff_bit_exact(params):
    """int8 donor STREAMS to an int8 recipient: scale pages ride each
    piece; continuation bit-exact. A bf16 receiver rejects at begin."""
    from distributed_gpu_inference_tpu.runtime.kv_handoff import (
        HandoffReceiver,
        StreamedExport,
    )

    kw = _kw(prefill_buckets=(32,), max_seq_len=192)
    donor = TPUEngine(CFG, EngineConfig(kv_cache_dtype="int8", **kw),
                      params=params)
    recv = TPUEngine(CFG, EngineConfig(kv_cache_dtype="int8", **kw),
                     params=params)
    oracle = TPUEngine(CFG, EngineConfig(kv_cache_dtype="int8", **kw),
                       params=params)
    prompt = [(i * 29 + 3) % 500 for i in range(80)]   # 3 chunks at 32
    want = oracle.generate([_req(prompt, 10)], use_multi_step=True)[0]

    rx = HandoffReceiver(recv)
    exp = StreamedExport(donor, _req(prompt, 10), key="i8", piece_blocks=1)
    result = None
    for msg in exp.messages():
        result = rx.handle(msg)
    assert result["state"] == "committed"
    slot = result["slot"]
    while recv.slots[slot] is not None and \
            recv.slots[slot].finish_reason is None:
        recv.decode_step()
    got = recv.finish_slot(slot)
    assert got.token_ids == want.token_ids, (got.token_ids, want.token_ids)

    fp = TPUEngine(CFG, EngineConfig(**kw), params=params)
    rx_fp = HandoffReceiver(fp)
    exp2 = StreamedExport(donor, _req(prompt, 4), key="i8b")
    gen = exp2.messages()
    with pytest.raises(ValueError, match="kv_cache_dtype mismatch"):
        rx_fp.handle(next(gen))
    gen.close()


def test_int8_device_migration_bit_exact(params):
    """Intra-slice PD with int8 pools: migrate_kv_device moves the EXACT
    int8 pages + their scale pages, so the recipient continues bit-for-bit
    what the donor would have produced (no requantization anywhere)."""
    from distributed_gpu_inference_tpu.runtime.kv_handoff import (
        migrate_kv_device,
    )

    donor = TPUEngine(CFG, EngineConfig(kv_cache_dtype="int8", **_kw()),
                      params=params)
    recv = TPUEngine(CFG, EngineConfig(kv_cache_dtype="int8", **_kw()),
                     params=params)
    oracle = TPUEngine(CFG, EngineConfig(kv_cache_dtype="int8", **_kw()),
                       params=params)
    prompt = [(i * 29 + 3) % 500 for i in range(24)]
    want = oracle.generate([_req(prompt, 12)], use_multi_step=True)[0]

    slot = donor.submit(_req(prompt, 12))
    for _ in range(3):
        donor.decode_step()
    dslot = migrate_kv_device(donor, recv, slot)
    donor.finish_slot(slot, cache=False)
    while recv.slots[dslot] is not None and \
            recv.slots[dslot].finish_reason is None:
        recv.decode_step()
    got = recv.finish_slot(dslot)
    assert got.token_ids == want.token_ids, (got.token_ids, want.token_ids)


def test_int8_decode_matches_own_prefill_continuation(params):
    """Internal consistency: decoding 1 token at a time equals the
    multi-step scan on the SAME int8 engine (write/read paths agree)."""
    a = TPUEngine(CFG, EngineConfig(kv_cache_dtype="int8", **_kw()),
                  params=params)
    b = TPUEngine(CFG, EngineConfig(kv_cache_dtype="int8", **_kw()),
                  params=params)
    prompt = [(i * 17 + 5) % 500 for i in range(24)]
    r1 = a.generate([_req(prompt, 10)], use_multi_step=False)[0]
    r2 = b.generate([_req(prompt, 10)], use_multi_step=True)[0]
    assert r1.token_ids == r2.token_ids
