"""Deterministic-seed concurrency tests.

SURVEY §5.2: the reference has no race-detection tooling and relies on
locks-by-construction; it recommends the new framework add at least
deterministic-seed concurrency tests. These drive the batcher, the worker
state lock, and the session manager under real concurrency and assert
determinism / mutual exclusion.
"""

import asyncio
import threading

import numpy as np
import pytest

# compile-heavy (jit/scan graphs): excluded from the fast CI gate
pytestmark = pytest.mark.slow

from distributed_gpu_inference_tpu.runtime.batcher import (
    BatcherConfig,
    ContinuousBatcher,
)
from distributed_gpu_inference_tpu.runtime.engine import EngineConfig, TPUEngine
from distributed_gpu_inference_tpu.utils.data_structures import (
    InferenceRequest,
    SamplingParams,
)

MODEL = "llama3-tiny"


def _engine():
    return TPUEngine(
        MODEL,
        EngineConfig(max_batch_size=4, max_seq_len=96, block_size=16,
                     prefill_buckets=(16, 32), dtype="float32"),
        seed=0,
    )


def _requests(n):
    rng = np.random.default_rng(7)
    return [
        InferenceRequest(
            request_id=f"r{i}",
            prompt_token_ids=rng.integers(1, 500, 24).tolist(),
            sampling=SamplingParams(max_new_tokens=8, temperature=0.0),
        )
        for i in range(n)
    ]


def _run_batch(engine, reqs):
    async def go():
        batcher = ContinuousBatcher(
            engine, BatcherConfig(default_timeout_s=120.0)
        )
        batcher.start()
        resps = await asyncio.gather(*(batcher.submit(r) for r in reqs))
        await batcher.stop()
        return {r.request_id: r.token_ids for r in resps}

    return asyncio.run(go())


def test_concurrent_batcher_is_deterministic():
    """12 concurrent greedy requests over 4 slots: two identical runs (same
    seeds, same arrival set) must produce identical tokens per request,
    regardless of admission interleaving."""
    out1 = _run_batch(_engine(), _requests(12))
    out2 = _run_batch(_engine(), _requests(12))
    assert set(out1) == set(out2)
    for rid in out1:
        assert out1[rid] == out2[rid], f"{rid} diverged across runs"
        assert len(out1[rid]) == 8


def test_worker_busy_claim_mutual_exclusion():
    """try_begin_job must admit exactly one concurrent holder."""
    from distributed_gpu_inference_tpu.utils.config import WorkerConfig
    from distributed_gpu_inference_tpu.worker.main import Worker
    from distributed_gpu_inference_tpu.utils.data_structures import WorkerState

    class _API:  # never used: no network in this test
        worker_id = auth_token = refresh_token = signing_secret = None

        def close(self):
            pass

    w = Worker(WorkerConfig(), api=_API())
    w.state = WorkerState.IDLE

    holders = []
    max_holders = []
    lock = threading.Lock()
    barrier = threading.Barrier(8)

    def hammer():
        barrier.wait()
        for _ in range(200):
            if w.try_begin_job():
                with lock:
                    holders.append(1)
                    max_holders.append(len(holders))
                with lock:
                    holders.pop()
                w.end_job()

    threads = [threading.Thread(target=hammer) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert max_holders, "no thread ever acquired the claim"
    assert max(max_holders) == 1  # never two holders at once


def test_stage_worker_sessions_under_threads():
    """Concurrent create/close on a stage worker must not corrupt the block
    free list (every block returns exactly once)."""
    from distributed_gpu_inference_tpu.comm.stage_worker import (
        PipelineStageWorker,
    )

    st = PipelineStageWorker(
        MODEL, (0, 2), num_blocks=128, max_blocks_per_seq=4, dtype="float32"
    )
    barrier = threading.Barrier(6)

    def churn(tid):
        barrier.wait()
        for i in range(50):
            sid = f"s{tid}-{i}"
            st.create_session(sid)
            st.close_session(sid)

    threads = [threading.Thread(target=churn, args=(t,)) for t in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    h = st.health()
    assert h["active_sessions"] == 0
    assert h["free_blocks"] == 127  # all returned (block 0 reserved)
