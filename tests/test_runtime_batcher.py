"""Continuous batcher flow (parity: reference
tests/test_worker_batch_processor_flow.py) on the tiny engine."""

import asyncio

import pytest

# compile-heavy (jit/scan graphs): excluded from the fast CI gate
pytestmark = pytest.mark.slow

from distributed_gpu_inference_tpu.runtime.batcher import (
    BatcherConfig,
    ContinuousBatcher,
)
from distributed_gpu_inference_tpu.runtime.engine import EngineConfig, TPUEngine
from distributed_gpu_inference_tpu.utils.data_structures import (
    InferenceRequest,
    SamplingParams,
)


@pytest.fixture(scope="module")
def engine():
    return TPUEngine(
        "llama3-tiny",
        EngineConfig(max_batch_size=4, max_seq_len=128,
                     prefill_buckets=(16, 32, 64), multi_step=4),
    )


def _req(prompt, max_new=6, priority=0):
    return InferenceRequest(
        prompt_token_ids=prompt, priority=priority,
        sampling=SamplingParams(max_new_tokens=max_new),
    )


def _run(coro):
    return asyncio.run(coro)


def test_single_request_roundtrip(engine):
    async def go():
        b = ContinuousBatcher(engine, BatcherConfig(max_wait_ms=1))
        b.start()
        resp = await b.submit(_req(list(range(10, 30))))
        await b.stop()
        return resp

    resp = _run(go())
    assert resp.ok and resp.completion_tokens == 6


def test_concurrent_requests_all_complete(engine):
    async def go():
        b = ContinuousBatcher(engine, BatcherConfig(max_wait_ms=2))
        b.start()
        resps = await asyncio.gather(
            *[b.submit(_req(list(range(i, i + 16)), max_new=4)) for i in range(10)]
        )
        stats = b.get_stats()
        await b.stop()
        return resps, stats

    resps, stats = _run(go())
    assert all(r.ok for r in resps)
    assert all(r.completion_tokens == 4 for r in resps)
    assert stats["completed"] == 10
    # continuous batching actually batched: fewer rounds than sequential worst
    assert stats["avg_occupancy"] > 1.0


def test_batched_matches_sequential(engine):
    prompts = [list(range(7, 27)), list(range(50, 80)), list(range(90, 120))]

    async def solo():
        b = ContinuousBatcher(engine, BatcherConfig(max_wait_ms=0))
        b.start()
        out = []
        for p in prompts:
            out.append(await b.submit(_req(p, max_new=5)))
        await b.stop()
        return out

    async def together():
        b = ContinuousBatcher(engine, BatcherConfig(max_wait_ms=5))
        b.start()
        out = await asyncio.gather(*[b.submit(_req(p, max_new=5)) for p in prompts])
        await b.stop()
        return out

    solo_resps = _run(solo())
    batch_resps = _run(together())
    for s, g in zip(solo_resps, batch_resps):
        assert s.token_ids == g.token_ids  # batching must not change results


def test_bad_request_resolves_with_error(engine):
    async def go():
        b = ContinuousBatcher(engine, BatcherConfig(max_wait_ms=0))
        b.start()
        resp = await b.submit(_req(list(range(200)), max_new=4))  # > bucket
        await b.stop()
        return resp

    resp = _run(go())
    assert not resp.ok and resp.error


def test_queue_limit_rejects(engine):
    async def go():
        b = ContinuousBatcher(engine, BatcherConfig(queue_limit=1, max_wait_ms=50))
        # not started: queue holds, second submit rejected
        t1 = asyncio.ensure_future(b.submit(_req(list(range(16)), max_new=2)))
        await asyncio.sleep(0.01)
        r2 = await b.submit(_req(list(range(16)), max_new=2))
        b.start()
        r1 = await t1
        await b.stop()
        return r1, r2

    r1, r2 = _run(go())
    assert r1.ok
    assert not r2.ok and "queue full" in r2.error


def test_priority_admission(engine):
    """With one slot, higher-priority queued request must be admitted first."""
    small = TPUEngine(
        "llama3-tiny",
        EngineConfig(max_batch_size=1, max_seq_len=64, prefill_buckets=(16, 32)),
    )

    order = []
    orig_submit_batch = small.submit_batch

    def tracking_submit_batch(requests, partial=False):
        order.extend(r.priority for r in requests)
        return orig_submit_batch(requests, partial=partial)

    small.submit_batch = tracking_submit_batch

    async def go():
        # ragged=False: this test spies on engine.submit_batch, the LEGACY
        # wave-admission entry point (ragged admissions bind through
        # submit_chunked_start instead; priority order under ragged is
        # covered in tests/test_ragged_attention.py)
        b = ContinuousBatcher(small, BatcherConfig(max_wait_ms=30,
                                                   ragged=False))
        lo = asyncio.ensure_future(
            b.submit(_req(list(range(16)), max_new=3, priority=0))
        )
        hi = asyncio.ensure_future(
            b.submit(_req(list(range(30, 46)), max_new=3, priority=5))
        )
        await asyncio.sleep(0.02)
        b.start()
        await asyncio.gather(lo, hi)
        await b.stop()
        return lo.result(), hi.result()

    lo, hi = _run(go())
    assert lo.ok and hi.ok
    assert order == [5, 0]  # high priority admitted to the single slot first


def test_adaptive_horizon_moves():
    eng = TPUEngine(
        "llama3-tiny",
        EngineConfig(max_batch_size=2, max_seq_len=128, prefill_buckets=(16, 32)),
    )

    async def go():
        b = ContinuousBatcher(
            eng,
            BatcherConfig(max_wait_ms=0, adaptive=True, multi_step=4,
                          target_step_latency_ms=10_000.0),  # far above real
        )
        b.start()
        await asyncio.gather(
            *[b.submit(_req(list(range(i, i + 16)), max_new=30)) for i in range(2)]
        )
        stats = b.get_stats()
        await b.stop()
        return stats

    stats = _run(go())
    # steps are far cheaper than target → horizon must have grown
    assert stats["horizon"] > 4


def test_busy_horizon_with_high_min_multi_step():
    """min_multi_step above busy_multi_step must snap to the smallest
    level, not crash (regression: empty max() in _engine_round)."""
    from distributed_gpu_inference_tpu.runtime.batcher import BatcherConfig

    cfg = BatcherConfig(min_multi_step=8)
    assert cfg.horizon_levels == (16, 64)
    # the snap logic itself: no level <= cap -> smallest level
    cap = min(16, cfg.busy_multi_step)
    eligible = [t for t in cfg.horizon_levels if t <= cap]
    assert (max(eligible) if eligible else min(cfg.horizon_levels)) == 16


def test_non_adaptive_honors_configured_multi_step():
    from distributed_gpu_inference_tpu.runtime.batcher import (
        BatcherConfig,
        ContinuousBatcher,
    )
    from distributed_gpu_inference_tpu.runtime.engine import (
        EngineConfig,
        TPUEngine,
    )

    eng = TPUEngine(
        "llama3-tiny",
        EngineConfig(max_batch_size=1, max_seq_len=64, block_size=16,
                     prefill_buckets=(16,), dtype="float32"),
    )
    b = ContinuousBatcher(eng, BatcherConfig(adaptive=False, multi_step=8))
    assert b._levels == (8,)
    assert b._horizon == 8.0


# ---------------------------------------------------------------------------
# Round 2: batched wave admission + chunk-interleaved long prompts
# ---------------------------------------------------------------------------


def test_wave_admission_one_prefill_call_per_bucket():
    """A same-bucket wave admits via ONE batched prefill device call
    (engine.submit_batch), not one per request (VERDICT r1 #3)."""
    eng = TPUEngine(
        "llama3-tiny",
        EngineConfig(max_batch_size=4, max_seq_len=128,
                     prefill_buckets=(16, 32), multi_step=4),
    )

    async def drive():
        # ragged=False pins the LEGACY wave path this test is about
        # (ragged-mode admission never calls submit_batch)
        b = ContinuousBatcher(eng, BatcherConfig(max_wait_ms=20.0,
                                                 multi_step=4,
                                                 ragged=False))
        b.start()
        before = eng.stats["prefill_calls"]
        reqs = [
            InferenceRequest(
                prompt_token_ids=list(range(10 + i, 26 + i)),
                sampling=SamplingParams(max_new_tokens=4),
            )
            for i in range(4)
        ]
        outs = await asyncio.gather(*(b.submit(r) for r in reqs))
        await b.stop()
        return outs, eng.stats["prefill_calls"] - before, b.get_stats()

    outs, prefill_calls, stats = asyncio.run(drive())
    assert all(o.error is None and o.completion_tokens == 4 for o in outs)
    # all 4 prompts share the 16-token bucket → exactly one prefill call
    assert prefill_calls == 1, prefill_calls
    assert stats["batched_waves"] == 1


def test_chunked_admission_interleaves_decode():
    """A long prompt admits chunk by chunk, and decode rounds for the other
    slots run BETWEEN its chunks — no decode stall longer than one chunk
    (VERDICT r1 #4)."""
    eng = TPUEngine(
        "llama3-tiny",
        EngineConfig(max_batch_size=2, max_seq_len=256,
                     prefill_buckets=(16, 32), multi_step=2,
                     enable_prefix_cache=False),
    )
    decode_calls_at_chunk = []
    orig_step = eng.submit_chunked_step

    def spy_step(adm):
        decode_calls_at_chunk.append(eng.stats["decode_calls"])
        return orig_step(adm)

    eng.submit_chunked_step = spy_step

    async def drive():
        # ragged=False pins the LEGACY chunk-interleaved admission this
        # test spies on (ragged mode co-dispatches chunk rows WITH decode
        # rows instead of interleaving separate dispatches)
        b = ContinuousBatcher(eng, BatcherConfig(max_wait_ms=1.0,
                                                 multi_step=2,
                                                 ragged=False))
        b.start()
        # short request keeps decoding while the long one admits
        short = b.submit(InferenceRequest(
            prompt_token_ids=list(range(10, 26)),
            sampling=SamplingParams(max_new_tokens=40),
        ))
        await asyncio.sleep(0.05)  # let the short one start decoding
        long = b.submit(InferenceRequest(
            prompt_token_ids=[(i * 7) % 500 for i in range(150)],
            sampling=SamplingParams(max_new_tokens=4),
        ))
        outs = await asyncio.gather(short, long)
        await b.stop()
        return outs, b.get_stats()

    (short_out, long_out), stats = asyncio.run(drive())
    assert short_out.error is None and short_out.completion_tokens == 40
    assert long_out.error is None and long_out.completion_tokens == 4
    assert long_out.prompt_tokens == 150
    assert stats["chunked_admissions"] == 1
    # 150 fresh tokens / 32-token max bucket → 5 chunk steps
    assert len(decode_calls_at_chunk) == 5, decode_calls_at_chunk
    # decode progressed between chunk steps (strictly increasing somewhere)
    assert decode_calls_at_chunk[-1] > decode_calls_at_chunk[0], \
        decode_calls_at_chunk


def test_second_long_prompt_does_not_starve_shorts():
    """While one chunked admission is in flight, a second long prompt at the
    head of the admission order must not block short requests from free
    slots (round-2 review finding)."""
    eng = TPUEngine(
        "llama3-tiny",
        EngineConfig(max_batch_size=3, max_seq_len=256,
                     prefill_buckets=(16, 32), multi_step=2,
                     enable_prefix_cache=False),
    )

    async def drive():
        # ragged=False: the one-chunked-admission-at-a-time bottleneck this
        # test guards only exists on the legacy path (ragged admissions
        # all ride the same round, so there is nothing to starve)
        b = ContinuousBatcher(eng, BatcherConfig(max_wait_ms=1.0,
                                                 multi_step=2,
                                                 ragged=False))
        b.start()
        long_a = b.submit(InferenceRequest(
            prompt_token_ids=[(i * 5) % 500 for i in range(120)],
            sampling=SamplingParams(max_new_tokens=3), priority=1,
        ))
        await asyncio.sleep(0.03)   # A's chunked admission starts
        # B (long, high priority → head of order) + shorts behind it
        long_b = b.submit(InferenceRequest(
            prompt_token_ids=[(i * 9) % 500 for i in range(120)],
            sampling=SamplingParams(max_new_tokens=3), priority=9,
        ))
        shorts = [b.submit(InferenceRequest(
            prompt_token_ids=list(range(10 + i, 26 + i)),
            sampling=SamplingParams(max_new_tokens=3),
        )) for i in range(2)]
        outs = await asyncio.gather(long_a, long_b, *shorts)
        stats = b.get_stats()
        await b.stop()
        return outs, stats

    outs, stats = asyncio.run(drive())
    assert all(o.error is None and o.completion_tokens == 3 for o in outs)
    assert stats["chunked_admissions"] == 2
