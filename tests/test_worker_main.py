"""Worker runtime: registration, engines, heartbeat, load control, drain.

Parity target: reference worker boot/poll behavior (SURVEY §3.1) — tested
hermetically with a fake API client and a stub engine, like the reference's
worker tests (no network, no model).
"""

import threading
import time
from typing import Any, Dict, List, Optional

import pytest

from distributed_gpu_inference_tpu.utils.config import (
    EngineModelConfig,
    WorkerConfig,
)
from distributed_gpu_inference_tpu.utils.data_structures import WorkerState
from distributed_gpu_inference_tpu.worker.api_client import APIError
from distributed_gpu_inference_tpu.worker.engines import register_engine
from distributed_gpu_inference_tpu.worker.engines.base import BaseEngine
from distributed_gpu_inference_tpu.worker.main import Worker, probe_topology


class StubEngine(BaseEngine):
    def __init__(self, config=None):
        super().__init__(config)
        self.loaded = False
        self.unloaded = False

    def load_model(self):
        self.loaded = True

    def inference(self, params):
        if params.get("boom"):
            raise RuntimeError("engine exploded")
        return {"echo": params}

    def unload(self):
        self.unloaded = True


class FakeAPI:
    """Implements the APIClient surface the Worker drives."""

    def __init__(self, jobs: Optional[List[Dict[str, Any]]] = None,
                 creds_valid: bool = False):
        self.worker_id = "w-1" if creds_valid else None
        self.auth_token = "tok" if creds_valid else None
        self.refresh_token = "ref" if creds_valid else None
        self.signing_secret = "sig" if creds_valid else None
        self.jobs = list(jobs or [])
        self.creds_valid = creds_valid
        self.completed: List[Dict[str, Any]] = []
        self.calls: List[str] = []
        self.heartbeat_response: Dict[str, Any] = {}
        self.remote_config: Dict[str, Any] = {"version": 0}

    def verify_credentials(self):
        self.calls.append("verify")
        return self.creds_valid

    def register(self, info):
        self.calls.append("register")
        self.registered_info = info
        self.worker_id = "w-new"
        self.auth_token = "tok2"
        self.refresh_token = "ref2"
        self.signing_secret = "sig2"
        return {
            "worker_id": "w-new", "auth_token": "tok2",
            "refresh_token": "ref2", "signing_secret": "sig2",
        }

    def refresh_credentials(self):
        self.calls.append("refresh")
        return {}

    def fetch_remote_config(self):
        self.calls.append("fetch_config")
        return self.remote_config

    def heartbeat(self, **kw):
        self.calls.append("heartbeat")
        self.last_heartbeat = kw
        return dict(self.heartbeat_response)

    def fetch_next_job(self):
        self.calls.append("poll")
        return self.jobs.pop(0) if self.jobs else None

    def complete_job(self, job_id, success, result=None, error=None):
        self.completed.append(
            {"job_id": job_id, "success": success, "result": result,
             "error": error}
        )
        return {"ok": True}

    def release_job(self, job_id):
        self.released = getattr(self, "released", [])
        self.released.append(job_id)

    def going_offline(self):
        self.calls.append("going_offline")

    def offline(self):
        self.calls.append("offline")
        return []

    def close(self):
        self.calls.append("close")


@pytest.fixture(autouse=True)
def stub_llm_engine():
    register_engine("llm", StubEngine)
    yield
    from distributed_gpu_inference_tpu.worker.engines import _OVERRIDES

    _OVERRIDES.pop("llm", None)


def _config(**kw) -> WorkerConfig:
    cfg = WorkerConfig(
        task_types=["llm"],
        engines={"llm": EngineModelConfig(engine="echo", model="llama3-tiny")},
        poll_interval_s=0.01,
        heartbeat_interval_s=30.0,
        **kw,
    )
    return cfg


def _worker(api: FakeAPI, **cfg_kw) -> Worker:
    return Worker(_config(**cfg_kw), api=api)


def test_register_new_worker_persists_credentials():
    api = FakeAPI()
    saved = {}
    w = Worker(_config(), api=api, on_credentials=saved.update)
    w.register()
    assert "register" in api.calls
    assert saved["worker_id"] == "w-new"
    assert api.registered_info["supported_types"] == ["llm"]
    assert "topology" in api.registered_info
    assert "fetch_config" in api.calls


def test_register_reuses_valid_credentials():
    api = FakeAPI(creds_valid=True)
    w = _worker(api)
    w.register()
    assert "register" not in api.calls
    assert "verify" in api.calls


def test_remote_config_overrides_load_control():
    api = FakeAPI(creds_valid=True)
    api.remote_config = {
        "version": 7,
        "load_control": {"acceptance_rate": 0.5, "max_jobs_per_hour": 10,
                         "working_hours": [9, 17]},
    }
    w = _worker(api)
    w.register()
    assert w.config.config_version == 7
    assert w.config.load_control.acceptance_rate == 0.5
    assert w.config.load_control.max_jobs_per_hour == 10
    assert w.config.load_control.working_hours == (9, 17)


def test_load_engines_drops_broken_type():
    class Broken(StubEngine):
        def load_model(self):
            from distributed_gpu_inference_tpu.worker.engines.base import (
                EngineLoadError,
            )

            raise EngineLoadError("no deps")

    register_engine("embedding", Broken)
    try:
        api = FakeAPI(creds_valid=True)
        cfg = _config()
        cfg.task_types = ["llm", "embedding"]
        w = Worker(cfg, api=api)
        w.load_engines()
        assert w.config.task_types == ["llm"]
        assert "llm" in w.engines and "embedding" not in w.engines
    finally:
        from distributed_gpu_inference_tpu.worker.engines import _OVERRIDES

        _OVERRIDES.pop("embedding", None)


def test_heartbeat_config_changed_triggers_refetch():
    api = FakeAPI(creds_valid=True)
    w = _worker(api)
    w.load_engines()
    api.heartbeat_response = {"config_changed": True}
    w._heartbeat_once()
    assert api.calls.count("fetch_config") == 1
    assert w.stats["heartbeats"] == 1


def test_heartbeat_401_refreshes_token():
    api = FakeAPI(creds_valid=True)

    def bad_heartbeat(**kw):
        api.calls.append("heartbeat")
        raise APIError(401, "expired")

    api.heartbeat = bad_heartbeat
    w = _worker(api)
    w._heartbeat_once()
    assert "refresh" in api.calls


def test_process_job_success_and_failure():
    api = FakeAPI(creds_valid=True)
    w = _worker(api)
    w.load_engines()
    w.state = WorkerState.IDLE
    assert w.try_begin_job()
    w.process_job({"id": "j1", "type": "llm", "params": {"x": 1}})
    assert api.completed[0]["success"] is True
    assert api.completed[0]["result"] == {"echo": {"x": 1}}
    assert w.stats["jobs_completed"] == 1
    assert w.state == WorkerState.IDLE

    assert w.try_begin_job()
    w.process_job({"id": "j2", "type": "llm", "params": {"boom": True}})
    assert api.completed[1]["success"] is False
    assert "exploded" in api.completed[1]["error"]
    assert w.stats["jobs_failed"] == 1


def test_try_begin_job_excludes_concurrent_work():
    api = FakeAPI(creds_valid=True)
    w = _worker(api)
    w.state = WorkerState.IDLE
    assert w.try_begin_job()
    assert not w.try_begin_job()        # second claim refused while BUSY
    w.end_job()
    assert w.try_begin_job()


def test_process_job_unknown_type_fails_cleanly():
    api = FakeAPI(creds_valid=True)
    w = _worker(api)
    w.load_engines()
    w.state = WorkerState.IDLE
    assert w.try_begin_job()
    w.process_job({"id": "j3", "type": "vision", "params": {}})
    assert api.completed[0]["success"] is False


def test_load_control_acceptance_rate_zero_rejects():
    api = FakeAPI(creds_valid=True)
    w = _worker(api)
    w.config.load_control.acceptance_rate = 0.0
    assert w.should_accept_job({"type": "llm"}) is False


def test_load_control_hourly_cap():
    api = FakeAPI(creds_valid=True)
    w = _worker(api)
    w.config.load_control.max_jobs_per_hour = 2
    now = time.time()
    w._hour_window = [now - 10, now - 20]
    assert w.should_accept_job({"type": "llm"}, now=now) is False
    # stale entries roll out of the window
    w._hour_window = [now - 4000, now - 20]
    assert w.should_accept_job({"type": "llm"}, now=now) is True


def test_load_control_cooldown():
    api = FakeAPI(creds_valid=True)
    w = _worker(api)
    w.config.load_control.cooldown_seconds = 30.0
    w._last_job_done_at = time.time() - 5
    assert w.should_accept_job({"type": "llm"}) is False
    w._last_job_done_at = time.time() - 60
    assert w.should_accept_job({"type": "llm"}) is True


def test_load_control_working_hours():
    api = FakeAPI(creds_valid=True)
    w = _worker(api)
    hour = time.localtime().tm_hour
    w.config.load_control.working_hours = ((hour + 1) % 24, (hour + 2) % 24)
    assert w.should_accept_job({"type": "llm"}) is False
    w.config.load_control.working_hours = (hour, (hour + 1) % 24)
    assert w.should_accept_job({"type": "llm"}) is True


def test_gated_worker_never_claims():
    """Job-independent gates are checked BEFORE fetching, so a gated worker
    doesn't claim-and-release head-of-queue work."""
    api = FakeAPI(creds_valid=True,
                  jobs=[{"id": "jx", "type": "llm", "params": {}}])
    w = _worker(api)
    w.load_engines()
    w.state = WorkerState.IDLE
    w.config.load_control.acceptance_rate = 0.0
    assert w._poll_once() is False
    assert "poll" not in api.calls          # never even fetched
    assert api.jobs                          # job still queued


def test_type_weight_release_is_one_shot():
    """A job released once by the probabilistic type throttle is ACCEPTED on
    re-encounter — no release/re-claim ping-pong starvation."""
    api = FakeAPI(creds_valid=True,
                  jobs=[{"id": "jw", "type": "llm", "params": {}},
                        {"id": "jw", "type": "llm", "params": {}}])
    w = _worker(api)
    w.load_engines()
    w.state = WorkerState.IDLE
    w.config.load_control.job_type_weights = {"llm": 0.0}  # always throttle
    assert w._poll_once() is False
    assert api.released == ["jw"]
    # the same job comes back: taken this time
    assert w._poll_once() is True
    assert api.completed[0]["job_id"] == "jw"


def test_rejected_job_released_not_failed():
    api = FakeAPI(creds_valid=True,
                  jobs=[{"id": "jr", "type": "llm", "params": {}}])
    w = _worker(api)
    w.load_engines()
    w.state = WorkerState.IDLE
    w.config.load_control.job_type_weights = {"llm": 0.0}
    assert w._poll_once() is False
    assert w.stats["jobs_rejected"] == 1
    # requeued for other workers — NOT completed as failed
    assert api.completed == []
    assert api.released == ["jr"]
    assert w.state == WorkerState.IDLE


def test_full_lifecycle_processes_jobs_then_drains():
    api = FakeAPI(
        creds_valid=True,
        jobs=[
            {"id": "a", "type": "llm", "params": {"n": 1}},
            {"id": "b", "type": "llm", "params": {"n": 2}},
        ],
    )
    w = _worker(api)
    t = threading.Thread(
        target=lambda: w.start(install_signal_handlers=False, block=True)
    )
    t.start()
    deadline = time.time() + 10
    while len(api.completed) < 2 and time.time() < deadline:
        time.sleep(0.01)
    w.request_shutdown()
    t.join(timeout=10)
    assert not t.is_alive()
    assert [c["job_id"] for c in api.completed] == ["a", "b"]
    assert "going_offline" in api.calls
    assert "offline" in api.calls
    assert "close" in api.calls
    assert w.state == WorkerState.OFFLINE
    assert w.engines["llm"].unloaded


def test_probe_topology_returns_valid():
    topo = probe_topology()
    assert topo.num_chips >= 1
    assert topo.chip_type in ("cpu", "v4", "v5e", "v5p", "v6e")


def test_get_status_shape():
    api = FakeAPI(creds_valid=True)
    w = _worker(api)
    st = w.get_status()
    assert st["state"] == "initializing"
    assert st["task_types"] == ["llm"]
    assert "topology" in st and "stats" in st


# -- TPU-aware onboarding probe (faked environments) -------------------------


def test_probe_tpu_runtime_reads_env(monkeypatch):
    from distributed_gpu_inference_tpu.worker.main import probe_tpu_runtime

    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-16")
    monkeypatch.setenv("TPU_WORKER_ID", "3")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
    monkeypatch.setenv("TPU_LIBRARY_PATH", "/opt/libtpu.so")
    r = probe_tpu_runtime()
    assert r["libtpu"] is True
    assert r["accelerator_type"] == "v5litepod-16"
    assert r["worker_id"] == "3"
    assert r["hosts"] == ["h0", "h1"]


def test_probe_topology_mesh_from_coords(monkeypatch):
    import distributed_gpu_inference_tpu.worker.main as wm

    class FakeDev:
        def __init__(self, coords):
            self.device_kind = "TPU v5e"
            self.coords = coords

    class FakeJax:
        @staticmethod
        def devices():
            # a 2x4 slice: coords span (2, 4, 1)
            return [FakeDev((x, y, 0)) for x in range(2) for y in range(4)]

    monkeypatch.setattr(wm, "probe_tpu_runtime", lambda: {
        "libtpu": True, "accel_devices": [], "accelerator_type": "",
        "worker_id": "", "hosts": [],
    })
    import sys
    monkeypatch.setitem(sys.modules, "jax", FakeJax())
    t = wm.probe_topology()
    assert t.chip_type == "v5e"
    assert t.num_chips == 8
    assert t.mesh_shape == (2, 4)
    assert t.peak_bf16_tflops == 197.0


def test_probe_topology_env_fallback_without_jax(monkeypatch):
    """Broken driver: jax raises, but libtpu + accelerator type declare a
    TPU host — register what the platform says, not 'cpu'."""
    import distributed_gpu_inference_tpu.worker.main as wm

    class Boom:
        def devices(self):
            raise RuntimeError("no backend")

        def __getattr__(self, k):
            raise RuntimeError("no backend")

    monkeypatch.setattr(wm, "probe_tpu_runtime", lambda: {
        "libtpu": True, "accel_devices": ["/dev/accel0"],
        "accelerator_type": "v5litepod-8", "worker_id": "", "hosts": [],
    })
    import sys
    monkeypatch.setitem(sys.modules, "jax", Boom())
    t = wm.probe_topology()
    assert t.chip_type == "v5e"
    assert t.num_chips == 8
    assert t.hbm_gb_per_chip == 16.0


def test_wizard_reports_runtime(monkeypatch):
    from distributed_gpu_inference_tpu.worker.cli import ConfigWizard
    import distributed_gpu_inference_tpu.worker.main as wm

    monkeypatch.setattr(wm, "probe_tpu_runtime", lambda: {
        "libtpu": True, "accel_devices": ["/dev/accel0"],
        "accelerator_type": "v5litepod-4", "worker_id": "", "hosts": [],
    })
    lines = []
    wiz = ConfigWizard(input_fn=lambda p: "", print_fn=lines.append)
    cfg = wiz.run()
    assert cfg is not None
    joined = "\n".join(lines)
    assert "libtpu=found" in joined
    assert "type=v5litepod-4" in joined
