"""Model-level correctness: prefill ≡ chunked prefill ≡ token-by-token decode.

The invariant that makes paged serving trustworthy: the logits for position i
must be identical whether computed in one prefill chunk, several chunks, or
one decode step at a time.
"""

import functools

import numpy as np
import pytest

# compile-heavy (jit/scan graphs): excluded from the fast CI gate
pytestmark = pytest.mark.slow

import jax
import jax.numpy as jnp

from distributed_gpu_inference_tpu.models import llama
from distributed_gpu_inference_tpu.models.configs import get_model_config

CFG = get_model_config("llama3-tiny", dtype="float32")
BLOCK = 16


@functools.partial(jax.jit, static_argnames=("last_only",))
def _fwd(params, toks, pos, kv, table, lens, last_only=True):
    return llama.forward_chunk(
        CFG, params, toks, pos, kv, table, lens,
        block_size=BLOCK, last_only=last_only,
    )


@pytest.fixture(scope="module")
def params():
    return llama.init_params(CFG, jax.random.PRNGKey(0), jnp.float32)


def _fresh(num_blocks=16):
    return llama.init_kv_pools(CFG, num_blocks, BLOCK, jnp.float32)


def _table(n_blocks_needed, start=1):
    t = np.zeros((1, 8), np.int32)
    t[0, :n_blocks_needed] = np.arange(start, start + n_blocks_needed)
    return jnp.asarray(t)


def _pos(lo, hi):
    return jnp.arange(lo, hi, dtype=jnp.int32)[None]


def test_full_prefill_vs_decode_steps(params):
    rng = np.random.default_rng(0)
    n = 24
    toks = rng.integers(0, CFG.vocab_size, n).astype(np.int32)

    out = _fwd(params, jnp.asarray(toks[None]), _pos(0, n), _fresh(), _table(2),
               jnp.asarray([n], jnp.int32), last_only=False)
    ref_logits = np.asarray(out.logits[0])  # [n, V]

    kv = _fresh()
    table = _table(2)
    got = []
    for i in range(n):
        o = _fwd(params, jnp.asarray([[toks[i]]]), jnp.asarray([[i]], jnp.int32),
                 kv, table, jnp.asarray([i + 1], jnp.int32), last_only=True)
        kv = o.kv
        got.append(np.asarray(o.logits[0, 0]))
    np.testing.assert_allclose(np.stack(got), ref_logits, rtol=1e-4, atol=1e-4)


def test_chunked_prefill_matches_full(params):
    rng = np.random.default_rng(1)
    n, split = 32, 20
    toks = rng.integers(0, CFG.vocab_size, n).astype(np.int32)

    full = _fwd(params, jnp.asarray(toks[None]), _pos(0, n), _fresh(), _table(2),
                jnp.asarray([n], jnp.int32), last_only=True)

    kv = _fresh()
    table = _table(2)
    o1 = _fwd(params, jnp.asarray(toks[None, :split]), _pos(0, split), kv, table,
              jnp.asarray([split], jnp.int32), last_only=True)
    o2 = _fwd(params, jnp.asarray(toks[None, split:]), _pos(split, n), o1.kv,
              table, jnp.asarray([n], jnp.int32), last_only=True)
    np.testing.assert_allclose(
        np.asarray(o2.logits), np.asarray(full.logits), rtol=1e-4, atol=1e-4
    )


def test_padded_prefill_matches_unpadded(params):
    rng = np.random.default_rng(2)
    n, bucket = 13, 32
    toks = rng.integers(0, CFG.vocab_size, n).astype(np.int32)

    plain = _fwd(params, jnp.asarray(toks[None]), _pos(0, n), _fresh(), _table(1),
                 jnp.asarray([n], jnp.int32), last_only=True)

    padded_toks = np.zeros((1, bucket), np.int32)
    padded_toks[0, :n] = toks
    pos = np.full((1, bucket), -1, np.int32)
    pos[0, :n] = np.arange(n)
    padded = _fwd(params, jnp.asarray(padded_toks), jnp.asarray(pos), _fresh(),
                  _table(1), jnp.asarray([n], jnp.int32), last_only=True)
    np.testing.assert_allclose(
        np.asarray(padded.logits), np.asarray(plain.logits), rtol=1e-4, atol=1e-4
    )


def test_batch_isolation(params):
    """Two sequences in one batch must not contaminate each other."""
    rng = np.random.default_rng(3)
    n = 16
    a = rng.integers(0, CFG.vocab_size, n).astype(np.int32)
    b = rng.integers(0, CFG.vocab_size, n).astype(np.int32)

    def solo(toks):
        return np.asarray(
            _fwd(params, jnp.asarray(toks[None]), _pos(0, n), _fresh(),
                 _table(1), jnp.asarray([n], jnp.int32), last_only=True).logits
        )

    la, lb = solo(a), solo(b)

    tables = jnp.asarray(np.array([[1, 0, 0, 0, 0, 0, 0, 0],
                                   [2, 0, 0, 0, 0, 0, 0, 0]], np.int32))
    both = _fwd(params, jnp.asarray(np.stack([a, b])),
                jnp.tile(np.arange(n, dtype=np.int32), (2, 1)), _fresh(), tables,
                jnp.asarray([n, n], jnp.int32), last_only=True)
    np.testing.assert_allclose(np.asarray(both.logits[0:1]), la, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(both.logits[1:2]), lb, rtol=1e-4, atol=1e-4)


def test_pipeline_stage_decomposition(params):
    """embed→forward_hidden_chunk(stage0)→(stage1)→project == full forward."""
    rng = np.random.default_rng(4)
    n = 16
    toks = rng.integers(0, CFG.vocab_size, n).astype(np.int32)

    full = _fwd(params, jnp.asarray(toks[None]), _pos(0, n), _fresh(), _table(1),
                jnp.asarray([n], jnp.int32), last_only=False)

    # split the 2-layer model into two 1-layer stages
    stage_params = [
        {**params, "layers": jax.tree.map(lambda x: x[0:1], params["layers"])},
        {**params, "layers": jax.tree.map(lambda x: x[1:2], params["layers"])},
    ]
    stage_cfg = get_model_config("llama3-tiny", num_layers=1, dtype="float32")
    kv0 = llama.init_kv_pools(stage_cfg, 16, BLOCK, jnp.float32)
    kv1 = jax.tree.map(jnp.copy, kv0)
    pos = _pos(0, n)
    lens = jnp.asarray([n], jnp.int32)
    hidden = llama.embed_tokens(stage_params[0], jnp.asarray(toks[None]), CFG)
    hidden, _ = llama.forward_hidden_chunk(
        CFG, stage_params[0], hidden, pos, kv0, _table(1), lens, block_size=BLOCK
    )
    hidden, _ = llama.forward_hidden_chunk(
        CFG, stage_params[1], hidden, pos, kv1, _table(1), lens, block_size=BLOCK
    )
    logits = llama.project_logits(CFG, stage_params[1], hidden)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full.logits), rtol=1e-4, atol=1e-4
    )
