"""KV handoff (prefill→decode migration): export/adopt correctness.

The invariant under test is the one the reference never implements (its KV
migration is a simulated sleep, ``server/app/services/pd_scheduler.py:462``):
a generation continued on the RECIPIENT engine after a real page transfer
must produce exactly the tokens the donor would have produced.
"""

import numpy as np
import pytest

# compile-heavy (jit/scan graphs): excluded from the fast CI gate
pytestmark = pytest.mark.slow

from distributed_gpu_inference_tpu.runtime.engine import EngineConfig, TPUEngine
from distributed_gpu_inference_tpu.runtime.kv_handoff import (
    adopt_kv,
    deserialize_handoff,
    export_slot_kv,
    serialize_handoff,
)
from distributed_gpu_inference_tpu.utils.data_structures import (
    InferenceRequest,
    SamplingParams,
)

MODEL = "llama3-tiny"
TOTAL_NEW = 12
PROMPT = [5, 17, 3, 99, 42, 7, 256, 31, 8, 120, 64]


def _cfg():
    return EngineConfig(
        max_batch_size=2, max_seq_len=64, block_size=16,
        prefill_buckets=(16, 32), dtype="float32",
    )


def _req():
    return InferenceRequest(
        prompt_token_ids=list(PROMPT),
        sampling=SamplingParams(max_new_tokens=TOTAL_NEW, temperature=0.0),
    )


@pytest.fixture(scope="module")
def shared_params():
    eng = TPUEngine(MODEL, _cfg(), seed=0)
    return eng.params


@pytest.fixture(scope="module")
def reference_tokens(shared_params):
    eng = TPUEngine(MODEL, _cfg(), params=shared_params, seed=0)
    resp = eng.generate([_req()])[0]
    assert len(resp.token_ids) == TOTAL_NEW
    return resp.token_ids


def _run_split(shared_params, split_at, via_wire):
    donor = TPUEngine(MODEL, _cfg(), params=shared_params, seed=0)
    slot = donor.submit(_req())
    steps = 0
    while len(donor.slots[slot].generated) < split_at:
        donor.decode_step()
        steps += 1
        assert steps < 64

    handoff = export_slot_kv(donor, slot)
    assert handoff.kv_len == int(donor._kv_lens[slot])
    assert handoff.pages.shape[0] == len(donor.manager.seq_blocks[
        donor.slots[slot].seq_id])
    if via_wire:
        handoff = deserialize_handoff(serialize_handoff(handoff))
    donor.finish_slot(slot, cache=False)

    recipient = TPUEngine(MODEL, _cfg(), params=shared_params, seed=0)
    new_slot = adopt_kv(recipient, handoff)
    while recipient.slots[new_slot] is not None and \
            recipient.slots[new_slot].finish_reason is None:
        recipient.decode_step()
    resp = recipient.finish_slot(new_slot)
    return resp


@pytest.mark.parametrize("split_at", [1, 5])
def test_handoff_continues_bit_exact(shared_params, reference_tokens, split_at):
    resp = _run_split(shared_params, split_at, via_wire=False)
    assert resp.token_ids == reference_tokens
    assert resp.finish_reason == "length"
    assert resp.prompt_tokens == len(PROMPT)


def test_handoff_over_wire_format(shared_params, reference_tokens):
    resp = _run_split(shared_params, 3, via_wire=True)
    assert resp.token_ids == reference_tokens


def test_wire_roundtrip_preserves_pages(shared_params):
    donor = TPUEngine(MODEL, _cfg(), params=shared_params, seed=0)
    slot = donor.submit(_req())
    donor.decode_step()
    h = export_slot_kv(donor, slot)
    h2 = deserialize_handoff(serialize_handoff(h))
    np.testing.assert_array_equal(
        np.asarray(h.pages, np.float32), np.asarray(h2.pages, np.float32)
    )
    assert h2.token_ids == h.token_ids
    assert h2.kv_len == h.kv_len
    assert h2.pending_token == h.pending_token
    assert h2.request.request_id == h.request.request_id


def test_adopt_rejects_model_mismatch(shared_params):
    donor = TPUEngine(MODEL, _cfg(), params=shared_params, seed=0)
    slot = donor.submit(_req())
    h = export_slot_kv(donor, slot)
    other = TPUEngine("llama3-mini", EngineConfig(
        max_batch_size=1, max_seq_len=64, block_size=16,
        prefill_buckets=(16, 32), dtype="float32"), seed=0)
    with pytest.raises(ValueError, match="model mismatch"):
        adopt_kv(other, h)


def test_adopt_rolls_back_on_full_engine(shared_params):
    donor = TPUEngine(MODEL, _cfg(), params=shared_params, seed=0)
    slot = donor.submit(_req())
    h = export_slot_kv(donor, slot)
    recipient = TPUEngine(MODEL, _cfg(), params=shared_params, seed=0)
    recipient.submit(_req())
    recipient.submit(_req())
    with pytest.raises(RuntimeError, match="no free slots"):
        adopt_kv(recipient, h)


# ---------------------------------------------------------------------------
# Sliding-window release state across handoff (ADVICE r1 #1)
# ---------------------------------------------------------------------------


def _wreq(prompt, max_new):
    return InferenceRequest(
        prompt_token_ids=list(prompt),
        sampling=SamplingParams(max_new_tokens=max_new, temperature=0.0),
    )


def test_handoff_carries_window_release_state():
    """A Mistral-style donor that already released out-of-window blocks must
    hand that state over: the recipient skips the garbage pages, pins the
    released chain entries to pad block 0, and continues bit-exact."""
    ecfg = EngineConfig(max_batch_size=2, max_seq_len=96,
                        prefill_buckets=(16, 32), multi_step=4,
                        enable_prefix_cache=True)
    prompt = [(i * 13) % 500 for i in range(30)]

    ref = TPUEngine("mistral-tiny", ecfg)   # sliding_window = 8
    want = ref.generate([_wreq(prompt, 24)])[0]

    donor = TPUEngine("mistral-tiny", ecfg)
    recv = TPUEngine("mistral-tiny", ecfg, params=donor.params)
    slot = donor.submit(_wreq(prompt, 24))
    for _ in range(10):  # decode past the window so blocks release
        donor.decode_step()
    h = export_slot_kv(donor, slot)
    assert h.window_front > 0, "donor must have released leading blocks"
    donor.finish_slot(slot, cache=False)

    dslot = adopt_kv(recv, deserialize_handoff(serialize_handoff(h)))
    # released chain entries are pinned to pad block 0 on the recipient
    seq_id = recv.slots[dslot].seq_id
    assert all(b == 0 for b in
               recv.manager.seq_blocks[seq_id][: h.window_front])
    assert recv.manager.seq_window_front[seq_id] == h.window_front
    while recv.slots[dslot] is not None and \
            recv.slots[dslot].finish_reason is None:
        recv.decode_step()
    got = recv.finish_slot(dslot)
    assert got.token_ids == want.token_ids


def test_adopted_window_chain_never_enters_radix():
    """Corner from ADVICE r1 #1: adopt with zero remaining budget →
    free_sequence(cache=True) must NOT insert the garbage-prefixed chain
    into the radix prefix index."""
    ecfg = EngineConfig(max_batch_size=2, max_seq_len=96,
                        prefill_buckets=(16, 32), multi_step=4,
                        enable_prefix_cache=True)
    prompt = [(i * 7) % 500 for i in range(30)]

    donor = TPUEngine("mistral-tiny", ecfg)
    recv = TPUEngine("mistral-tiny", ecfg, params=donor.params)
    slot = donor.submit(_wreq(prompt, 12))
    for _ in range(11):
        donor.decode_step()
    h = export_slot_kv(donor, slot)
    assert h.window_front > 0
    donor.finish_slot(slot, cache=False)

    dslot = adopt_kv(recv, deserialize_handoff(serialize_handoff(h)))
    # finish immediately — no decode step ever runs on the recipient
    recv.finish_slot(dslot, cache=True)
    # a new prompt sharing the prefix must MISS (the truncated chain is not
    # a valid prefix), not silently reuse garbage KV
    probe_slot = recv.submit(_wreq(prompt, 2))
    assert recv.slots[probe_slot].cached_tokens == 0
    recv.finish_slot(probe_slot, cache=False)
