"""KV handoff (prefill→decode migration): export/adopt correctness.

The invariant under test is the one the reference never implements (its KV
migration is a simulated sleep, ``server/app/services/pd_scheduler.py:462``):
a generation continued on the RECIPIENT engine after a real page transfer
must produce exactly the tokens the donor would have produced.
"""

import numpy as np
import pytest

from distributed_gpu_inference_tpu.runtime.engine import EngineConfig, TPUEngine
from distributed_gpu_inference_tpu.runtime.kv_handoff import (
    adopt_kv,
    deserialize_handoff,
    export_slot_kv,
    serialize_handoff,
)
from distributed_gpu_inference_tpu.utils.data_structures import (
    InferenceRequest,
    SamplingParams,
)

MODEL = "llama3-tiny"
TOTAL_NEW = 12
PROMPT = [5, 17, 3, 99, 42, 7, 256, 31, 8, 120, 64]


def _cfg():
    return EngineConfig(
        max_batch_size=2, max_seq_len=64, block_size=16,
        prefill_buckets=(16, 32), dtype="float32",
    )


def _req():
    return InferenceRequest(
        prompt_token_ids=list(PROMPT),
        sampling=SamplingParams(max_new_tokens=TOTAL_NEW, temperature=0.0),
    )


@pytest.fixture(scope="module")
def shared_params():
    eng = TPUEngine(MODEL, _cfg(), seed=0)
    return eng.params


@pytest.fixture(scope="module")
def reference_tokens(shared_params):
    eng = TPUEngine(MODEL, _cfg(), params=shared_params, seed=0)
    resp = eng.generate([_req()])[0]
    assert len(resp.token_ids) == TOTAL_NEW
    return resp.token_ids


def _run_split(shared_params, split_at, via_wire):
    donor = TPUEngine(MODEL, _cfg(), params=shared_params, seed=0)
    slot = donor.submit(_req())
    steps = 0
    while len(donor.slots[slot].generated) < split_at:
        donor.decode_step()
        steps += 1
        assert steps < 64

    handoff = export_slot_kv(donor, slot)
    assert handoff.kv_len == int(donor._kv_lens[slot])
    assert handoff.pages.shape[0] == len(donor.manager.seq_blocks[
        donor.slots[slot].seq_id])
    if via_wire:
        handoff = deserialize_handoff(serialize_handoff(handoff))
    donor.finish_slot(slot, cache=False)

    recipient = TPUEngine(MODEL, _cfg(), params=shared_params, seed=0)
    new_slot = adopt_kv(recipient, handoff)
    while recipient.slots[new_slot] is not None and \
            recipient.slots[new_slot].finish_reason is None:
        recipient.decode_step()
    resp = recipient.finish_slot(new_slot)
    return resp


@pytest.mark.parametrize("split_at", [1, 5])
def test_handoff_continues_bit_exact(shared_params, reference_tokens, split_at):
    resp = _run_split(shared_params, split_at, via_wire=False)
    assert resp.token_ids == reference_tokens
    assert resp.finish_reason == "length"
    assert resp.prompt_tokens == len(PROMPT)


def test_handoff_over_wire_format(shared_params, reference_tokens):
    resp = _run_split(shared_params, 3, via_wire=True)
    assert resp.token_ids == reference_tokens


def test_wire_roundtrip_preserves_pages(shared_params):
    donor = TPUEngine(MODEL, _cfg(), params=shared_params, seed=0)
    slot = donor.submit(_req())
    donor.decode_step()
    h = export_slot_kv(donor, slot)
    h2 = deserialize_handoff(serialize_handoff(h))
    np.testing.assert_array_equal(
        np.asarray(h.pages, np.float32), np.asarray(h2.pages, np.float32)
    )
    assert h2.token_ids == h.token_ids
    assert h2.kv_len == h.kv_len
    assert h2.pending_token == h.pending_token
    assert h2.request.request_id == h.request.request_id


def test_adopt_rejects_model_mismatch(shared_params):
    donor = TPUEngine(MODEL, _cfg(), params=shared_params, seed=0)
    slot = donor.submit(_req())
    h = export_slot_kv(donor, slot)
    other = TPUEngine("llama3-mini", EngineConfig(
        max_batch_size=1, max_seq_len=64, block_size=16,
        prefill_buckets=(16, 32), dtype="float32"), seed=0)
    with pytest.raises(ValueError, match="model mismatch"):
        adopt_kv(other, h)


def test_adopt_rolls_back_on_full_engine(shared_params):
    donor = TPUEngine(MODEL, _cfg(), params=shared_params, seed=0)
    slot = donor.submit(_req())
    h = export_slot_kv(donor, slot)
    recipient = TPUEngine(MODEL, _cfg(), params=shared_params, seed=0)
    recipient.submit(_req())
    recipient.submit(_req())
    with pytest.raises(RuntimeError, match="no free slots"):
        adopt_kv(recipient, h)
