"""Gray-failure immunity (round 18): slow-worker quarantine, deadline
abandonment, and hedged dispatch.

The dangerous replica is ALIVE: it heartbeats on time while answering 10x
slow (``degrade``), noisily slow (``jitter``) or 5xx-at-probability
(``flaky``). This suite covers the whole defense in layers:

- **Schedules**: gray kinds live in their own tuple — historical fleet
  seeds stay bit-identical — and ``--replay SEED --gray`` reconstructs a
  failing suite seed's exact schedule.
- **HealthService units**: the healthy → suspect → quarantined →
  probation machine with injected clocks — relative scoring, hysteresis,
  grace, the quarantine-fraction cap, canary-budgeted re-admission, and
  the all-or-nothing live config push.
- **Plane integration** (no engines): quarantine excluded from discovery
  and claims, hedge hints offered to opted-in deadline traffic, the
  health gauges/counters, and the disabled path byte-identical to the
  pre-round-18 build.
- **Batcher abandonment units** (fake engine): the hopeless-work
  projection math and the typed ``deadline_abandoned`` resolution —
  NEVER for deadline-less requests, no-op when disabled.
- **DirectServer**: hedge-cancel exactly-once, the reserved
  ``_cancel_evt`` slot, and the heartbeat telemetry channel's
  drain-as-deltas contract.
- **SDK**: the hedged two-leg race — first winner cancels the loser,
  fast primaries never fire the hedge, deadline-less requests keep the
  single-POST path.
- **KV handoff wire**: deadlines cross the PD boundary as absolute
  times (omitted, not null, when unset).

Heavy replays carry ``slow`` + ``gray_chaos`` (HEAVY CI shard, ``pytest
-m gray_chaos``); everything else stays tier-1 unmarked.
"""

import asyncio
import contextlib
import threading
import time
from typing import Any, Dict, List, Optional

import httpx
import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from distributed_gpu_inference_tpu.runtime.batcher import (
    BatcherConfig,
    ContinuousBatcher,
)
from distributed_gpu_inference_tpu.runtime.kv_handoff import (
    KVHandoff,
    deserialize_handoff,
    serialize_handoff,
)
from distributed_gpu_inference_tpu.sdk.client import InferenceClient
from distributed_gpu_inference_tpu.server.health import (
    HEALTHY,
    PROBATION,
    QUARANTINED,
    SUSPECT,
    HealthConfig,
    HealthService,
)
from distributed_gpu_inference_tpu.testing.faults import (
    ALL_FLEET_EVENT_KINDS,
    FLEET_EVENT_KINDS,
    GRAY_CHAOS_KINDS,
    GRAY_CHAOS_WORKERS,
    GRAY_EVENT_KINDS,
    FleetEvent,
    FleetFaultPlan,
    _replay_main,
)
from distributed_gpu_inference_tpu.testing.harness import (
    DEFAULT_FLEET_ENGINE,
    LiveControlPlane,
    LiveFleet,
)
from distributed_gpu_inference_tpu.utils.data_structures import (
    InferenceRequest,
    SamplingParams,
    WorkerState,
)
from distributed_gpu_inference_tpu.worker.api_client import APIClient
from distributed_gpu_inference_tpu.worker.direct_server import DirectServer

N_SEEDS = 25


# ---------------------------------------------------------------------------
# schedule determinism + replay CLI (cheap, tier-1)
# ---------------------------------------------------------------------------


def _gray_plan(seed: int) -> FleetFaultPlan:
    return FleetFaultPlan(seed, n_workers=GRAY_CHAOS_WORKERS,
                          kinds=GRAY_CHAOS_KINDS)


def test_gray_plan_same_seed_same_schedule():
    for seed in range(N_SEEDS):
        a, b = _gray_plan(seed), _gray_plan(seed)
        assert a.events == b.events, seed
        assert a.events, seed


def test_gray_plan_covers_every_gray_kind_across_suite_seeds():
    kinds = set()
    for seed in range(N_SEEDS):
        kinds |= {e.kind for e in _gray_plan(seed).events}
    assert {"degrade", "jitter", "flaky", "kill"} <= kinds


def test_gray_kinds_are_separate_from_historical_tuples():
    """Adding gray kinds must not perturb a single historical seed: they
    live in their own tuple, and the default fleet generator never draws
    them."""
    assert not set(GRAY_EVENT_KINDS) & set(FLEET_EVENT_KINDS)
    assert set(GRAY_EVENT_KINDS) <= set(ALL_FLEET_EVENT_KINDS)
    for seed in range(40):
        for e in FleetFaultPlan(seed).events:
            assert e.kind not in GRAY_EVENT_KINDS, (seed, e)


def test_gray_plan_event_parameters_are_sane():
    """Degrade windows stretch to ≥ half the run (the persistent gray
    failure quarantine exists to catch); jitter/flaky probabilities stay
    in the generator's [0.25, 0.75] band."""
    saw_degrade = False
    for seed in range(60):
        plan = _gray_plan(seed)
        for e in plan.events:
            if e.kind == "degrade":
                saw_degrade = True
                assert e.duration_s >= plan.duration_s * 0.5 - 1e-9, (seed, e)
                assert e.delay_s > 0.0
            if e.kind in ("jitter", "flaky"):
                assert 0.25 <= e.prob <= 0.75, (seed, e)
            if e.kind == "jitter":
                assert e.delay_s > 0.0
    assert saw_degrade


def test_gray_replay_cli_reconstructs_suite_schedules(capsys):
    assert _replay_main(["--replay", "7", "--gray"]) == 0
    out = capsys.readouterr().out
    for line in _gray_plan(7).describe():
        assert line in out


def test_gray_replay_cli_rejects_mixed_suite_flags(capsys):
    with pytest.raises(SystemExit):
        _replay_main(["--replay", "1", "--gray", "--pd"])
    capsys.readouterr()


# ---------------------------------------------------------------------------
# HealthService: the state machine, hermetic (injected clocks)
# ---------------------------------------------------------------------------


def _svc(**over: Any):
    cfg = HealthConfig(enabled=True, min_samples=3, min_peers=2,
                       suspect_ratio=3.0, clear_ratio=1.5, grace_s=1.0,
                       probation_after_s=2.0, canary_budget=3)
    for k, v in over.items():
        setattr(cfg, k, v)
    transitions: List[tuple] = []
    svc = HealthService(cfg, on_transition=lambda w, f, t:
                        transitions.append((w, f, t)))
    return svc, transitions


def _feed(svc: HealthService, wid: str, ms: float, n: int,
          now: float) -> None:
    for _ in range(n):
        svc.observe(wid, ms, now=now)


def test_disabled_service_is_inert():
    svc = HealthService()          # default config: enabled=False
    svc.observe("a", 500.0)
    svc.observe_error("a", 10)
    svc.ingest("a", {"direct": {"recent_ms": [900.0], "new_errors": 3}},
               body={"hb_rtt_ms": 400.0})
    svc.evaluate()
    assert svc.states() == {}      # not even accumulating
    assert svc.snapshot()["workers"] == {}
    assert not svc.is_quarantined("a")
    assert svc.allow_canary("a")
    ids = ["a", "b"]
    assert svc.admissible(ids) is ids    # passthrough, untouched


def test_slow_worker_walks_the_full_state_machine_and_readmits():
    svc, trans = _svc()
    t0 = 1000.0
    for wid, ms in (("a", 10.0), ("b", 12.0), ("c", 300.0)):
        _feed(svc, wid, ms, 4, t0)
    svc.evaluate(now=t0)
    assert svc.state("c") == SUSPECT
    assert svc.state("a") == HEALTHY and svc.state("b") == HEALTHY
    # suspects still serve through the grace window
    assert not svc.is_quarantined("c")
    assert svc.allow_canary("c")
    svc.evaluate(now=t0 + 0.5)                 # grace not yet elapsed
    assert svc.state("c") == SUSPECT
    svc.evaluate(now=t0 + 1.0)                 # grace_s=1.0 elapsed
    assert svc.state("c") == QUARANTINED
    assert svc.is_quarantined("c")
    assert not svc.allow_canary("c")
    assert svc.admissible(["a", "b", "c"]) == ["a", "b"]
    svc.evaluate(now=t0 + 3.0)                 # probation_after_s=2.0
    assert svc.state("c") == PROBATION
    assert not svc.is_quarantined("c")         # routing gate is quarantine-only
    # canary evidence comes back fast → re-admitted
    _feed(svc, "c", 11.0, 3, t0 + 3.5)
    svc.evaluate(now=t0 + 4.0)
    assert svc.state("c") == HEALTHY
    assert trans == [("c", HEALTHY, SUSPECT),
                     ("c", SUSPECT, QUARANTINED),
                     ("c", QUARANTINED, PROBATION),
                     ("c", PROBATION, HEALTHY)]


def test_probation_requarantines_on_slow_canaries():
    svc, trans = _svc()
    t0 = 1000.0
    for wid, ms in (("a", 10.0), ("b", 12.0), ("c", 300.0)):
        _feed(svc, wid, ms, 4, t0)
    svc.evaluate(now=t0)
    svc.evaluate(now=t0 + 1.0)
    svc.evaluate(now=t0 + 3.0)
    assert svc.state("c") == PROBATION
    _feed(svc, "c", 400.0, 3, t0 + 3.5)        # canaries still slow
    svc.evaluate(now=t0 + 4.0)
    assert svc.state("c") == QUARANTINED
    assert trans[-1] == ("c", PROBATION, QUARANTINED)


def test_probation_canary_traffic_is_budget_bounded():
    svc, _ = _svc(canary_budget=2)
    t0 = 1000.0
    for wid, ms in (("a", 10.0), ("b", 12.0), ("c", 300.0)):
        _feed(svc, wid, ms, 4, t0)
    svc.evaluate(now=t0)
    svc.evaluate(now=t0 + 1.0)
    svc.evaluate(now=t0 + 3.0)
    assert svc.state("c") == PROBATION
    assert svc.allow_canary("c")
    assert svc.allow_canary("c")
    assert not svc.allow_canary("c")           # budget of 2 exhausted
    # ranking (admissible) never charges the budget — only selection does
    assert svc.admissible(["a", "c"]) == ["a", "c"]


def test_quarantine_cap_bounds_the_blast_radius():
    """At most max_quarantined_frac of the scored fleet quarantines at
    once: with 5 scored workers and the default 0.34, the cap is 1 — two
    simultaneous stragglers cannot take out 40% of the fleet."""
    svc, _ = _svc()
    t0 = 1000.0
    for wid, ms in (("a", 10.0), ("b", 11.0), ("e", 12.0),
                    ("c", 300.0), ("d", 320.0)):
        _feed(svc, wid, ms, 4, t0)
    svc.evaluate(now=t0)
    assert svc.state("c") == SUSPECT and svc.state("d") == SUSPECT
    svc.evaluate(now=t0 + 1.0)
    states = svc.states()
    held = [w for w in ("c", "d") if states[w] == QUARANTINED]
    assert len(held) == 1, states
    # the other straggler holds at suspect until headroom frees
    other = "d" if held == ["c"] else "c"
    assert states[other] == SUSPECT


def test_server_errors_score_as_synthetic_slow_samples():
    """A flaky replica failing FAST must not look healthy: each 5xx
    scores as error_sample_ms."""
    svc, _ = _svc()
    t0 = 1000.0
    _feed(svc, "a", 10.0, 4, t0)
    _feed(svc, "b", 12.0, 4, t0)
    svc.observe_error("c", count=4, now=t0)
    svc.evaluate(now=t0)
    assert svc.state("c") == SUSPECT
    snap = svc.snapshot(now=t0)
    assert snap["workers"]["c"]["p95_ms"] == svc.cfg.error_sample_ms
    # the synthetic-sample burst is capped (a counter glitch must not
    # flood the ring)
    svc.observe_error("d", count=10_000, now=t0)
    assert svc.snapshot(now=t0)["workers"]["d"]["samples"] <= 64


def test_no_baseline_without_enough_peers():
    """One worker alone is never judged — there is nothing to be
    relatively slow against."""
    svc, _ = _svc()
    t0 = 1000.0
    _feed(svc, "only", 5000.0, 10, t0)
    svc.evaluate(now=t0)
    assert svc.state("only") == HEALTHY
    assert svc.snapshot(now=t0)["baseline_p95_ms"] == 0.0


def test_admissible_falls_back_when_filter_would_empty():
    svc, _ = _svc()
    t0 = 1000.0
    for wid, ms in (("a", 10.0), ("b", 12.0), ("c", 300.0)):
        _feed(svc, wid, ms, 4, t0)
    svc.evaluate(now=t0)
    svc.evaluate(now=t0 + 1.0)
    assert svc.state("c") == QUARANTINED
    # availability beats purity: a slow answer over none
    assert svc.admissible(["c"]) == ["c"]
    assert svc.admissible(["a", "c"]) == ["a"]


def test_forget_clears_gray_state():
    svc, _ = _svc()
    _feed(svc, "a", 10.0, 4, 1000.0)
    assert "a" in svc.states()
    svc.forget("a")
    assert svc.states() == {}


def test_observe_rejects_garbage_samples():
    svc, _ = _svc()
    for bad in (float("nan"), float("inf"), -5.0, "abc", None):
        svc.observe("a", bad, now=1000.0)
    assert svc.snapshot(now=1000.0)["workers"] == {}


def test_ingest_reads_every_heartbeat_channel_and_never_raises():
    svc, _ = _svc()
    t0 = 1000.0
    svc.ingest("w", {"direct": {"recent_ms": [10.0, 20.0],
                                "new_errors": 2}},
               body={"hb_rtt_ms": 5.0}, now=t0)
    # 1 RTT + 2 direct latencies + 2 synthetic error samples
    assert svc.snapshot(now=t0)["workers"]["w"]["samples"] == 5
    # worker-supplied garbage degrades to skipped samples, never raises
    svc.ingest("w", {"direct": {"recent_ms": "zz", "new_errors": "x"}},
               body={"hb_rtt_ms": "bad"}, now=t0)
    svc.ingest("w", "not-a-dict", body=None, now=t0)
    assert svc.snapshot(now=t0)["workers"]["w"]["samples"] == 5


def test_config_update_validates_all_before_applying_any():
    cfg = HealthConfig()
    cfg.update({"suspect_ratio": 2.0, "clear_ratio": 1.2})
    assert cfg.suspect_ratio == 2.0 and cfg.clear_ratio == 1.2
    # hysteresis rails: clear must stay strictly below suspect
    with pytest.raises(ValueError, match="clear_ratio"):
        cfg.update({"clear_ratio": 5.0})
    assert cfg.clear_ratio == 1.2
    # all-or-nothing: the valid window_s must not land when min_samples
    # in the same push is rejected
    with pytest.raises(ValueError):
        cfg.update({"window_s": 120.0, "min_samples": 0})
    assert cfg.window_s == 60.0
    with pytest.raises(ValueError):
        cfg.update({"max_quarantined_frac": 1.5})
    # env/YAML tooling stringifies bools — coerce by content
    cfg.update({"enabled": "on", "hedge": "false"})
    assert cfg.enabled is True and cfg.hedge is False
    with pytest.raises(ValueError, match="not a boolean"):
        cfg.update({"enabled": "maybe"})
    assert cfg.enabled is True


def test_hedge_delay_derives_from_baseline_and_clamps():
    svc, _ = _svc()
    # no baseline yet: the clamp floor answers
    assert svc.hedge_delay_ms(now=1000.0) == svc.cfg.hedge_delay_min_ms
    _feed(svc, "a", 100.0, 4, 1000.0)
    _feed(svc, "b", 100.0, 4, 1000.0)
    assert svc.hedge_delay_ms(now=1000.0) == pytest.approx(150.0)  # 1.5x
    svc.cfg.hedge_delay_factor = 1000.0
    assert svc.hedge_delay_ms(now=1000.0) == svc.cfg.hedge_delay_max_ms


# ---------------------------------------------------------------------------
# plane integration: discovery, claims, hedge hints, metrics (no engines)
# ---------------------------------------------------------------------------


def _register(cp: LiveControlPlane, name: str) -> APIClient:
    api = APIClient(cp.url, backoff_s=0.0)
    api.register({"name": name, "region": "us-west",
                  "supported_types": ["llm"], "supports_direct": True,
                  "direct_url": f"http://{name}.example:8471"})
    return api


def _metric(cp: LiveControlPlane, name: str) -> str:
    text = httpx.get(f"{cp.url}/metrics").text
    return "\n".join(
        line for line in text.splitlines() if line.startswith(name)
    )


def _put_health(cp: LiveControlPlane, **cfg: Any) -> httpx.Response:
    return httpx.put(f"{cp.url}/api/v1/admin/health", json=cfg)


def _direct_samples(ms: float, n: int = 5) -> Dict[str, Any]:
    return {"direct": {"recent_ms": [ms] * n, "new_errors": 0,
                       "hedge_cancels": 0}}


def test_health_disabled_keeps_discovery_byte_identical():
    """The default-OFF contract: telemetry may arrive, nothing reads it;
    the nearest response carries the pre-round-18 fields exactly even
    when the client asks for a hedge; no health series render."""
    with LiveControlPlane() as cp:
        a = _register(cp, "a")
        b = _register(cp, "b")
        a.heartbeat(status="idle", engine_stats=_direct_samples(5.0),
                    hb_rtt_ms=1.0)
        b.heartbeat(status="idle", engine_stats=_direct_samples(900.0))
        r = httpx.get(f"{cp.url}/api/v1/jobs/direct/nearest",
                      params={"hedge": "1"})
        assert r.status_code == 200
        assert set(r.json().keys()) == {"worker_id", "direct_url",
                                        "region", "client_region"}
        assert cp.state.health.states() == {}       # nothing accumulated
        assert _metric(cp, "worker_health_state") == ""
        g = httpx.get(f"{cp.url}/api/v1/admin/health").json()
        assert g["enabled"] is False
        assert g["snapshot"]["workers"] == {}
        a.close()
        b.close()


def _quarantine_b(cp: LiveControlPlane):
    """3 workers; b ships slow direct samples until quarantined."""
    a, b, c = _register(cp, "a"), _register(cp, "b"), _register(cp, "c")
    a.heartbeat(status="idle", engine_stats=_direct_samples(10.0))
    c.heartbeat(status="idle", engine_stats=_direct_samples(12.0))
    b.heartbeat(status="idle", engine_stats=_direct_samples(500.0))
    # any beat re-evaluates; grace_s=0 lets suspect escalate on the next
    a.heartbeat(status="idle")
    assert cp.state.health.state(b.worker_id) == QUARANTINED
    return a, b, c


def test_quarantined_worker_excluded_from_discovery_and_claims():
    with LiveControlPlane() as cp:
        assert _put_health(cp, enabled=True, min_samples=3, min_peers=2,
                           grace_s=0.0, probation_after_s=600.0
                           ).status_code == 200
        a, b, c = _quarantine_b(cp)
        # discovery never hands out the quarantined replica
        for _ in range(6):
            r = httpx.get(f"{cp.url}/api/v1/jobs/direct/nearest")
            assert r.json()["worker_id"] != b.worker_id
        # the claim path is gated too: b polls and gets nothing, a claims
        job_id = cp.call(cp.state.store.create_job(
            {"type": "llm", "params": {"prompt": "x"}}
        ))
        assert b.fetch_next_job() is None
        job = a.fetch_next_job()
        assert job is not None and job["id"] == job_id
        # scrape-time gauges: per-worker state codes + the transition trail
        assert f'worker="{b.worker_id}"}} 2.0' in _metric(
            cp, "worker_health_state"
        )
        assert 'from="suspect",to="quarantined"' in _metric(
            cp, "health_transitions_total"
        )
        # fleet strength counts the quarantined replica as degraded:
        # 2 serving / 3 registered
        line = _metric(cp, "fleet_degraded")
        assert abs(float(line.split()[-1]) - 2.0 / 3.0) < 1e-6, line
        # a clean deregistration supersedes gray state
        r = httpx.delete(
            f"{cp.url}/api/v1/admin/workers/{b.worker_id}")
        assert r.status_code == 200
        assert b.worker_id not in cp.state.health.states()
        for api in (a, b, c):
            api.close()


def test_hedge_hint_offered_only_to_opted_in_requests():
    with LiveControlPlane() as cp:
        assert _put_health(cp, enabled=True, hedge=True, min_samples=3,
                           min_peers=2).status_code == 200
        a, b = _register(cp, "a"), _register(cp, "b")
        a.heartbeat(status="idle", engine_stats=_direct_samples(10.0))
        b.heartbeat(status="idle", engine_stats=_direct_samples(12.0))
        r = httpx.get(f"{cp.url}/api/v1/jobs/direct/nearest",
                      params={"hedge": "1"})
        j = r.json()
        assert "hedge" in j
        assert j["hedge"]["worker_id"] != j["worker_id"]
        assert j["hedge"]["direct_url"]
        assert j["hedge"]["delay_ms"] >= \
            cp.state.health.cfg.hedge_delay_min_ms
        assert 'outcome="offered"' in _metric(cp, "hedges_total")
        # no opt-in → no hedge field, even with both switches on
        r2 = httpx.get(f"{cp.url}/api/v1/jobs/direct/nearest")
        assert "hedge" not in r2.json()
        # hedge switch off → the opt-in is ignored
        assert _put_health(cp, hedge=False).status_code == 200
        r3 = httpx.get(f"{cp.url}/api/v1/jobs/direct/nearest",
                       params={"hedge": "1"})
        assert "hedge" not in r3.json()
        a.close()
        b.close()


def test_admin_health_put_rejects_bad_pushes_atomically():
    with LiveControlPlane() as cp:
        r = _put_health(cp, suspect_ratio=2.0, clear_ratio=5.0)
        assert r.status_code == 400
        g = httpx.get(f"{cp.url}/api/v1/admin/health").json()
        assert g["suspect_ratio"] == 3.0 and g["clear_ratio"] == 1.5
        assert _put_health(cp, enabled=True, window_s=30.0
                           ).status_code == 200
        g = httpx.get(f"{cp.url}/api/v1/admin/health").json()
        assert g["enabled"] is True and g["window_s"] == 30.0


# ---------------------------------------------------------------------------
# batcher: hopeless-work abandonment (fake engine, no decode loop)
# ---------------------------------------------------------------------------


class _PoolEngine:
    """The minimal engine surface an UNSTARTED batcher touches: items
    stay in the heap, so the deadline scan is exercised in isolation."""

    supports_ragged = False
    slots: List[Any] = []

    def request_fits_pool(self, request: InferenceRequest) -> bool:
        return True


def _mk_batcher(**over: Any) -> ContinuousBatcher:
    return ContinuousBatcher(
        _PoolEngine(), BatcherConfig(abandon_deadlines=True, **over)
    )


def _req(deadline_s: Optional[float], arrival_ago: float = 0.0,
         max_new: int = 64) -> InferenceRequest:
    return InferenceRequest(
        prompt_token_ids=[1, 2, 3],
        sampling=SamplingParams(max_new_tokens=max_new),
        arrival_time=time.time() - arrival_ago,
        deadline_s=deadline_s,
    )


def test_deadline_hopeless_projection_math():
    b = _mk_batcher(deadline_grace_s=0.5)
    b.stats["step_latency_ema_ms"] = 100.0
    now = 1000.0
    late = InferenceRequest(prompt_token_ids=[1],
                            sampling=SamplingParams(max_new_tokens=50),
                            arrival_time=now - 10.0, deadline_s=5.0)
    assert b._deadline_hopeless(late, 50, now)          # 5s past, 5s left
    assert not b._deadline_hopeless(late, 0, now)       # finishing frees 0
    # just past the deadline but 1 token lands inside the grace window
    close = InferenceRequest(prompt_token_ids=[1],
                             sampling=SamplingParams(max_new_tokens=100),
                             arrival_time=now - 0.1, deadline_s=0.0)
    assert not b._deadline_hopeless(close, 1, now)
    assert b._deadline_hopeless(close, 100, now)
    # before the deadline: never hopeless, whatever the projection
    early = InferenceRequest(prompt_token_ids=[1],
                             sampling=SamplingParams(max_new_tokens=100),
                             arrival_time=now, deadline_s=60.0)
    assert not b._deadline_hopeless(early, 10_000, now)
    # deadline-less: the explicit None guard, not just +inf arithmetic
    free = InferenceRequest(prompt_token_ids=[1],
                            sampling=SamplingParams(max_new_tokens=100),
                            arrival_time=now - 9999.0, deadline_s=None)
    assert not b._deadline_hopeless(free, 10_000, now)
    # feature off: not even a clock comparison
    b.cfg.abandon_deadlines = False
    assert not b._deadline_hopeless(late, 50, now)


def test_scan_abandons_hopeless_queued_work_with_typed_error():
    async def body():
        b = _mk_batcher()
        b.stats["step_latency_ema_ms"] = 200.0
        task = asyncio.ensure_future(
            b.submit(_req(deadline_s=5.0, arrival_ago=30.0)))
        await asyncio.sleep(0.01)          # enqueue runs; loop not started
        assert len(b._heap) == 1
        await b._scan_deadlines()
        resp = await asyncio.wait_for(task, 5.0)
        assert resp.error_code == "deadline_abandoned"
        assert resp.finish_reason == "abort"
        assert "grace" in (resp.error or "")
        assert b._heap == []
        assert b.stats["abandoned"] == 1
        assert b.stats["completed"] == 1

    asyncio.run(body())


def test_deadline_less_requests_are_never_abandoned():
    async def body():
        b = _mk_batcher()
        b.stats["step_latency_ema_ms"] = 1000.0
        hopeless = asyncio.ensure_future(
            b.submit(_req(deadline_s=1.0, arrival_ago=60.0)))
        free = asyncio.ensure_future(
            b.submit(_req(deadline_s=None, arrival_ago=60.0)))
        await asyncio.sleep(0.01)
        assert len(b._heap) == 2
        await b._scan_deadlines()
        resp = await asyncio.wait_for(hopeless, 5.0)
        assert resp.error_code == "deadline_abandoned"
        # the deadline-less neighbor is untouched, still queued
        assert len(b._heap) == 1
        assert not free.done()
        assert b.stats["abandoned"] == 1
        free.cancel()
        with contextlib.suppress(asyncio.CancelledError):
            await free

    asyncio.run(body())


def test_scan_is_a_noop_when_disabled():
    async def body():
        b = _mk_batcher()
        b.cfg.abandon_deadlines = False
        b.stats["step_latency_ema_ms"] = 1000.0
        task = asyncio.ensure_future(
            b.submit(_req(deadline_s=1.0, arrival_ago=60.0)))
        await asyncio.sleep(0.01)
        await b._scan_deadlines()
        assert len(b._heap) == 1           # expired, but the knob is off
        assert not task.done()
        assert b.stats["abandoned"] == 0
        task.cancel()
        with contextlib.suppress(asyncio.CancelledError):
            await task

    asyncio.run(body())


def test_abandonment_knobs_are_live_pushable():
    b = ContinuousBatcher(_PoolEngine(), BatcherConfig())
    assert b.cfg.abandon_deadlines is False          # default OFF
    assert b.cfg.deadline_grace_s == 0.5
    b.reconfigure(abandon_deadlines="true", deadline_grace_s="0.25")
    assert b.cfg.abandon_deadlines is True
    assert b.cfg.deadline_grace_s == 0.25
    b.reconfigure(abandon_deadlines="off")
    assert b.cfg.abandon_deadlines is False


def test_abandonment_knobs_ride_the_serving_remote_config():
    from distributed_gpu_inference_tpu.utils.config import ServingConfig
    from distributed_gpu_inference_tpu.worker.engines.llm import (
        SERVING_REMOTE_KEYS,
    )

    sv = ServingConfig()
    assert sv.abandon_deadlines is False and sv.deadline_grace_s == 0.5
    assert SERVING_REMOTE_KEYS["abandon_deadlines"] == "abandon_deadlines"
    assert SERVING_REMOTE_KEYS["deadline_grace_s"] == "deadline_grace_s"


# ---------------------------------------------------------------------------
# direct server: hedge cancel exactly-once + the telemetry channel
# ---------------------------------------------------------------------------


class _DSWorker:
    """FakeWorker with a blockable engine: ``wait_cancel`` requests park
    on the server-minted cancel event until /inference/cancel flips it."""

    def __init__(self, text: str = "ok", block: bool = False):
        self.state = WorkerState.IDLE
        self.engines = {"llm": self}
        self.text = text
        self.block = block
        self.seen: List[Dict[str, Any]] = []

    def try_begin_job(self):
        if self.state != WorkerState.IDLE:
            return False
        self.state = WorkerState.BUSY
        return True

    def end_job(self):
        if self.state == WorkerState.BUSY:
            self.state = WorkerState.IDLE

    def inference(self, params):
        self.seen.append(dict(params))
        evt = params.get("_cancel_evt")
        if self.block and evt is not None:
            cancelled = evt.wait(8.0)
            return {"text": "cancelled" if cancelled else "ran-to-end"}
        if params.get("boom"):
            raise RuntimeError("kaboom")
        return {"text": self.text}

    def get_status(self):
        return {"state": self.state.value, "task_types": ["llm"]}


async def _make_client(worker):
    ds = DirectServer(worker)
    client = TestClient(TestServer(ds.make_app()))
    await client.start_server()
    return client, ds


def test_hedge_cancel_is_exactly_once():
    async def body():
        w = _DSWorker(block=True)
        client, ds = await _make_client(w)
        t = asyncio.ensure_future(client.post(
            "/inference",
            json={"type": "llm", "params": {"hedge_key": "k1"}},
        ))
        for _ in range(200):
            if "k1" in ds._cancels:
                break
            await asyncio.sleep(0.01)
        assert "k1" in ds._cancels
        r1 = await client.post("/inference/cancel",
                               json={"hedge_key": "k1"})
        assert (await r1.json())["cancelled"] is True
        # the second racer tidying up sees False — the counter moves once
        r2 = await client.post("/inference/cancel",
                               json={"hedge_key": "k1"})
        assert (await r2.json())["cancelled"] is False
        resp = await asyncio.wait_for(t, 10.0)
        assert resp.status == 200
        assert (await resp.json())["result"]["text"] == "cancelled"
        assert ds.stats["hedge_cancels"] == 1
        # post-completion the key is unregistered: idempotent no-op 200
        r3 = await client.post("/inference/cancel",
                               json={"hedge_key": "k1"})
        assert r3.status == 200
        assert (await r3.json())["cancelled"] is False
        # the engine saw the server-minted Event, never the wire key
        seen = w.seen[0]
        assert "hedge_key" not in seen
        assert isinstance(seen.get("_cancel_evt"), threading.Event)
        await client.close()

    asyncio.run(body())


def test_cancel_unknown_key_and_bad_json():
    async def body():
        w = _DSWorker()
        client, ds = await _make_client(w)
        r = await client.post("/inference/cancel",
                              json={"hedge_key": "never-existed"})
        assert r.status == 200
        assert (await r.json())["cancelled"] is False
        r = await client.post("/inference/cancel", data=b"not json")
        assert r.status == 400
        assert ds.stats["hedge_cancels"] == 0
        await client.close()

    asyncio.run(body())


def test_wire_supplied_cancel_event_is_discarded():
    """``_cancel_evt`` is server-owned: a client smuggling one in must
    not reach the engine (it would crash the batcher's cancel hook)."""
    async def body():
        w = _DSWorker()
        client, _ = await _make_client(w)
        r = await client.post(
            "/inference",
            json={"type": "llm", "params": {"_cancel_evt": "evil"}},
        )
        assert r.status == 200
        assert "_cancel_evt" not in w.seen[0]
        await client.close()

    asyncio.run(body())


def test_direct_telemetry_drains_as_deltas():
    async def body():
        w = _DSWorker()
        client, ds = await _make_client(w)
        r = await client.post("/inference",
                              json={"type": "llm", "params": {}})
        assert r.status == 200
        r = await client.post("/inference",
                              json={"type": "llm",
                                    "params": {"boom": 1}})
        assert r.status == 500
        ws = ds.wire_stats()
        assert len(ws["recent_ms"]) == 1       # the success's wall time
        assert ws["recent_ms"][0] >= 0.0
        assert ws["new_errors"] == 1           # the engine 500
        assert ws["hedge_cancels"] == 0        # cumulative counter
        # drained: the next beat ships only what happened since
        ws2 = ds.wire_stats()
        assert ws2["recent_ms"] == [] and ws2["new_errors"] == 0
        await client.close()

    asyncio.run(body())


# ---------------------------------------------------------------------------
# SDK: the hedged two-leg race against two live direct servers
# ---------------------------------------------------------------------------


def _start_direct(worker: _DSWorker):
    ds = DirectServer(worker, host="127.0.0.1", port=0)
    ds.start()
    port = ds._runner.addresses[0][1]
    return ds, f"http://127.0.0.1:{port}"


def test_sdk_hedged_race_first_winner_cancels_loser():
    slow = _DSWorker(block=True)
    fast = _DSWorker(text="fast")
    ds_slow, url_slow = _start_direct(slow)
    ds_fast, url_fast = _start_direct(fast)
    c = InferenceClient("http://plane.invalid:9", backoff_s=0.0,
                        max_retries=0)
    try:
        c._get_nearest_worker = lambda **kw: {
            "worker_id": "p", "direct_url": url_slow, "region": "r",
            "hedge": {"worker_id": "h", "direct_url": url_fast,
                      "delay_ms": 30.0},
        }
        res = c._try_direct("llm", {"prompt": "x", "deadline_s": 5.0})
        assert res == {"text": "fast"}         # the hedge won the race
        # the losing primary was cancelled at the server, exactly once
        deadline = time.time() + 3.0
        while time.time() < deadline and \
                ds_slow.stats["hedge_cancels"] != 1:
            time.sleep(0.02)
        assert ds_slow.stats["hedge_cancels"] == 1
        # both legs carried the request; the keys never reached engines
        assert slow.seen and "hedge_key" not in slow.seen[0]
    finally:
        c.close()
        ds_slow.stop()
        ds_fast.stop()


def test_sdk_fast_primary_never_fires_the_hedge():
    primary = _DSWorker(text="primary")
    backup = _DSWorker(text="backup")
    ds_p, url_p = _start_direct(primary)
    ds_b, url_b = _start_direct(backup)
    c = InferenceClient("http://plane.invalid:9", backoff_s=0.0,
                        max_retries=0)
    try:
        c._get_nearest_worker = lambda **kw: {
            "worker_id": "p", "direct_url": url_p, "region": "r",
            "hedge": {"worker_id": "h", "direct_url": url_b,
                      "delay_ms": 500.0},
        }
        res = c._try_direct("llm", {"prompt": "x", "deadline_s": 5.0})
        assert res == {"text": "primary"}
        time.sleep(0.1)
        assert ds_b.stats["requests"] == 0     # hedge leg never fired
        assert ds_p.stats["hedge_cancels"] == 0
    finally:
        c.close()
        ds_p.stop()
        ds_b.stop()


def test_sdk_deadline_less_requests_keep_the_single_post_path():
    primary = _DSWorker(text="primary")
    backup = _DSWorker(text="backup")
    ds_p, url_p = _start_direct(primary)
    ds_b, url_b = _start_direct(backup)
    c = InferenceClient("http://plane.invalid:9", backoff_s=0.0,
                        max_retries=0)
    try:
        calls: Dict[str, Any] = {}

        def fake_nearest(**kw):
            calls.update(kw)
            return {"worker_id": "p", "direct_url": url_p, "region": "r",
                    "hedge": {"worker_id": "h", "direct_url": url_b,
                              "delay_ms": 1.0}}

        c._get_nearest_worker = fake_nearest
        res = c._try_direct("llm", {"prompt": "x"})
        assert res == {"text": "primary"}
        assert calls.get("hedge") is False     # discovery not asked to hedge
        assert ds_b.stats["requests"] == 0     # a stray hint is ignored
        # the unhedged POST carries the raw params — no cancel key minted
        assert "hedge_key" not in primary.seen[0]
        assert "_cancel_evt" not in primary.seen[0]
    finally:
        c.close()
        ds_p.stop()
        ds_b.stop()


def test_sdk_both_legs_failing_falls_back_to_queued_path():
    slow = _DSWorker(block=True)
    fast = _DSWorker()
    slow.state = WorkerState.BUSY              # both legs reject with 503
    fast.state = WorkerState.BUSY
    ds_s, url_s = _start_direct(slow)
    ds_f, url_f = _start_direct(fast)
    c = InferenceClient("http://plane.invalid:9", backoff_s=0.0,
                        max_retries=0)
    try:
        c._get_nearest_worker = lambda **kw: {
            "worker_id": "p", "direct_url": url_s, "region": "r",
            "hedge": {"worker_id": "h", "direct_url": url_f,
                      "delay_ms": 5.0},
        }
        assert c._try_direct("llm",
                             {"prompt": "x", "deadline_s": 5.0}) is None
    finally:
        c.close()
        ds_s.stop()
        ds_f.stop()


# ---------------------------------------------------------------------------
# KV handoff wire: deadlines cross the PD boundary as absolute times
# ---------------------------------------------------------------------------


def _mk_handoff(deadline_s: Optional[float],
                arrival_ago: float = 0.0) -> KVHandoff:
    req = InferenceRequest(
        prompt_token_ids=[1, 2, 3],
        sampling=SamplingParams(max_new_tokens=8),
        arrival_time=time.time() - arrival_ago,
        deadline_s=deadline_s,
    )
    return KVHandoff(
        request=req, model_name="m", block_size=4,
        token_ids=[1, 2, 3, 7], kv_len=3, pending_token=7,
        prompt_len=3, generated=[7], start_time=req.arrival_time,
        first_token_time=None,
        pages=np.zeros((1, 2, 2, 1, 4, 2), dtype=np.float32),
    )


def test_handoff_wire_carries_absolute_deadline():
    h = _mk_handoff(deadline_s=30.0, arrival_ago=2.0)
    data = serialize_handoff(h)
    assert b"deadline_at" in data
    out = deserialize_handoff(data)
    # re-derived against the receiver's fresh arrival_time, the ABSOLUTE
    # instant is preserved: elapsed handoff time stays spent
    assert out.request.deadline_s is not None
    assert out.request.deadline_s < 30.0
    assert out.request.deadline_at == pytest.approx(
        h.request.deadline_at, abs=1e-6)


def test_handoff_wire_omits_deadline_when_unset():
    h = _mk_handoff(deadline_s=None)
    data = serialize_handoff(h)
    # omitted, not null: deadline-less wires are byte-identical to the
    # pre-deadline format
    assert b"deadline_at" not in data
    out = deserialize_handoff(data)
    assert out.request.deadline_s is None
    assert out.request.deadline_at == float("inf")


def test_handoff_wire_clamps_already_missed_deadlines():
    h = _mk_handoff(deadline_s=1.0, arrival_ago=100.0)
    out = deserialize_handoff(serialize_handoff(h))
    assert out.request.deadline_s == 0.0       # missed, but never negative


def test_checkpoint_resume_keeps_edf_ordering_across_migration():
    """A failover-resumed job must re-enter the EDF heap ordered by its
    ORIGINAL absolute deadline — not with the fresh arrival's infinite
    (or re-anchored) slack."""
    from distributed_gpu_inference_tpu.runtime.engine import (
        PreemptedSequence,
    )

    orig = InferenceRequest(
        prompt_token_ids=[1, 2, 3],
        sampling=SamplingParams(max_new_tokens=8),
        arrival_time=time.time() - 5.0,
        deadline_s=8.0,
    )
    pre = PreemptedSequence(
        request=orig, prompt_len=3, generated=[7], slot_key=(0, 0),
        start_time=orig.arrival_time, first_token_time=None,
        cached_tokens=0,
    )
    resumed = PreemptedSequence.from_wire(pre.to_wire()).request
    # the absolute instant survives the wire; the 5s already elapsed on
    # the dead worker stays spent
    assert resumed.deadline_at == pytest.approx(orig.deadline_at,
                                                abs=1e-6)
    assert resumed.deadline_s == pytest.approx(3.0, abs=0.5)
    # EDF: the resumed request outranks a same-priority fresh
    # deadline-less arrival AND a fresh later-deadline one
    fresh_late = InferenceRequest(
        prompt_token_ids=[4], sampling=SamplingParams(max_new_tokens=8),
        deadline_s=60.0,
    )
    fresh_none = InferenceRequest(
        prompt_token_ids=[5], sampling=SamplingParams(max_new_tokens=8),
    )
    ranked = sorted(
        [fresh_none, fresh_late, resumed],
        key=lambda r: (-r.priority, r.deadline_at, r.arrival_time),
    )
    assert ranked[0] is resumed
    assert ranked[-1] is fresh_none


# ---------------------------------------------------------------------------
# the 25-seed composed suite (HEAVY: slow + gray_chaos)
# ---------------------------------------------------------------------------

GRAY_FLEET_ENGINE = {
    **DEFAULT_FLEET_ENGINE,
    "serving": {**DEFAULT_FLEET_ENGINE["serving"], "max_preemptions": 8},
}

# aggressive thresholds so a ~6s chaos window can walk the full state
# machine: judged after 4 samples, escalation after 0.3s of suspicion,
# probation opens 3s into quarantine
GRAY_HEALTH = dict(enabled=True, window_s=20.0, min_samples=4,
                   min_peers=2, suspect_ratio=3.0, clear_ratio=1.5,
                   grace_s=0.3, probation_after_s=3.0, canary_budget=4)


def _enable_health(plane: LiveControlPlane, **over: Any) -> None:
    r = httpx.put(f"{plane.url}/api/v1/admin/health",
                  json={**GRAY_HEALTH, **over})
    assert r.status_code == 200, r.text


@pytest.fixture(scope="module")
def gray_fleet():
    with LiveFleet(n=GRAY_CHAOS_WORKERS,
                   engine_config=GRAY_FLEET_ENGINE) as f:
        _enable_health(f.plane)
        yield f


def _health_state(plane: LiveControlPlane, wid: str) -> Optional[str]:
    r = httpx.get(f"{plane.url}/api/v1/admin/health")
    return (r.json()["snapshot"]["workers"].get(wid) or {}).get("state")


def _await_health_state(plane: LiveControlPlane, wid: str, want,
                        timeout_s: float) -> str:
    states = want if isinstance(want, (set, tuple)) else {want}
    deadline = time.time() + timeout_s
    seen = None
    while time.time() < deadline:
        seen = _health_state(plane, wid)      # GET re-evaluates server-side
        if seen in states:
            return seen
        time.sleep(0.05)
    raise AssertionError(f"worker {wid} never reached {states}: {seen}")


@pytest.mark.slow
@pytest.mark.gray_chaos
@pytest.mark.parametrize("seed", range(N_SEEDS))
def test_gray_chaos_seeded(gray_fleet, seed):
    """One seeded gray replay: degrade/jitter/flaky composed with clean
    kills on a 3-replica fleet with quarantine live — nothing lost,
    exactly-once SSE offsets, outputs byte-identical to a calm replay."""
    from tests.test_fleet_chaos import (
        _assert_no_lost_or_duplicated_jobs,
        _await_quiet,
        _calm_reference,
        _drive_open_loop,
        _heal,
        _suite_prompts,
    )

    plan = _gray_plan(seed)
    assert plan.events == _gray_plan(seed).events      # determinism
    prompts = _suite_prompts(seed, 9)
    gray_fleet.run_chaos(plan)
    try:
        records = _drive_open_loop(gray_fleet, prompts, seed=seed,
                                   max_tokens=7)
    finally:
        gray_fleet.wait_chaos(timeout_s=180.0)
        _heal(gray_fleet)
    assert [k for _, k, _ in plan.trace] == [e.kind for e in plan.events]
    _await_quiet(gray_fleet)
    _assert_no_lost_or_duplicated_jobs(gray_fleet)
    _calm_reference(gray_fleet, records, max_tokens=7)
    assert all(m.alive for m in gray_fleet.members)


@pytest.mark.slow
@pytest.mark.gray_chaos
def test_degraded_worker_quarantined_then_readmitted_live():
    """The tentpole walk on a LIVE fleet: one replica degrades (alive,
    heartbeating, 0.3s/request slow); the plane quarantines it off the
    shipped latency samples, opens probation, and re-admits it once its
    fresh evidence comes back clean."""
    with LiveFleet(n=3, engine_config=GRAY_FLEET_ENGINE) as fl:
        _enable_health(fl.plane)
        target = fl.members[0]
        urls = [
            f"http://127.0.0.1:{m.server._runner.addresses[0][1]}"
            for m in fl.members
        ]
        # warm every engine BEFORE the chaos clock starts: first-request
        # JIT compile is seconds on CPU and would eat the degrade window
        with httpx.Client(timeout=30.0) as c:
            for u in urls:
                c.post(u + "/inference", json={
                    "type": "llm",
                    "params": {"prompt": "warm abcdef",
                               "max_new_tokens": 2},
                })
        plan = FleetFaultPlan(0, n_workers=3, duration_s=8.0,
                              kinds=GRAY_CHAOS_KINDS)
        plan.events = [FleetEvent(0.0, "degrade", 0, duration_s=6.0,
                                  delay_s=0.3)]
        fl.run_chaos(plan)
        try:
            # direct traffic on every replica: the degraded one's samples
            # arrive 0.3s slow while its peers set a fast baseline
            with httpx.Client(timeout=15.0) as c:
                for i in range(8):
                    for u in urls:
                        with contextlib.suppress(httpx.HTTPError):
                            c.post(u + "/inference", json={
                                "type": "llm",
                                "params": {"prompt": f"gray{i} abcdef",
                                           "max_new_tokens": 2},
                            })
            got = _await_health_state(
                fl.plane, target.worker_id,
                {SUSPECT, QUARANTINED, PROBATION}, timeout_s=10.0,
            )
            assert got, "degraded worker never flagged"
        finally:
            fl.wait_chaos()
        # the full escalation is in the transition trail even if polling
        # missed an intermediate state
        deadline = time.time() + 10.0
        while time.time() < deadline and 'to="quarantined"' not in \
                _metric(fl.plane, "health_transitions_total"):
            httpx.get(f"{fl.plane.url}/api/v1/admin/health")
            time.sleep(0.05)
        trail = _metric(fl.plane, "health_transitions_total")
        assert 'from="healthy",to="suspect"' in trail
        assert 'from="suspect",to="quarantined"' in trail
        # chaos over: fresh samples (heartbeat RTTs + fast direct
        # traffic) walk it through probation back to healthy
        with httpx.Client(timeout=15.0) as c:
            for i in range(4):
                with contextlib.suppress(httpx.HTTPError):
                    c.post(urls[0] + "/inference", json={
                        "type": "llm",
                        "params": {"prompt": f"calm{i} abcdef",
                                   "max_new_tokens": 2},
                    })
        assert _await_health_state(fl.plane, target.worker_id, HEALTHY,
                                   timeout_s=20.0) == HEALTHY
        trail = _metric(fl.plane, "health_transitions_total")
        assert 'from="quarantined",to="probation"' in trail
        assert 'from="probation",to="healthy"' in trail
        # the replica was never killed — alive and registered throughout
        assert target.alive
