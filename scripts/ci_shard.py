#!/usr/bin/env python
"""Deterministic test-file sharding for the full CI gate.

Usage: python scripts/ci_shard.py SHARD_INDEX NUM_SHARDS
Prints the test files of the shard (interleaved assignment so heavy files
spread across shards), for xargs into pytest. Run from the repo root
(globs tests/).

Known-heavy files — compile-heavy model/parallel suites and the seeded
chaos-replay suite (tests/test_chaos_scenarios.py, 50 replays per
scenario) — are placed at the head of the interleave order, so every
shard receives at most ceil(len(HEAVY)/NUM_SHARDS) of them instead of a
chance clustering that blows one shard's wall clock.
"""
import argparse
import pathlib

# ordered heaviest-first; files absent from the checkout are skipped
HEAVY = [
    "tests/test_plane_chaos.py",         # 25-seed plane-cohort chaos
    #   (multi-plane LiveFleet: plane kills/partitions/latency while
    #   open-loop queued+SSE traffic runs over a shared job store)
    "tests/test_overload_chaos.py",      # 25-seed overload-under-chaos
    #   (10x free-tier burst + admission ladder + kill/restart + the
    #   live-fleet autoscaler legs)
    "tests/test_pd_chaos.py",            # 25-seed PD-split handoff chaos
    #   (role-tagged LiveFleet + streamed-handoff kills/corruption)
    "tests/test_fleet_chaos.py",         # 25-seed LiveFleet chaos replays
    #   (real multi-worker fleet + kill/partition/pressure under load)
    "tests/test_gray_chaos.py",          # 25-seed gray-failure replays
    #   (degrade/jitter/flaky + kills with quarantine live, plus the
    #   quarantine/probation/re-admission walk on a live fleet)
    "tests/test_io_chaos.py",            # 25-seed durable-tier io chaos
    #   (disk_full/io_error/corrupt/torn storms on a spill-tiered fleet
    #   + the fully-dark-tier and disk-full degraded-mode walks)
    "tests/test_chaos_scenarios.py",     # 50-seed replays per scenario
    "tests/test_worker_failover_chaos.py",  # 25-seed kill-mid-stream e2e
    "tests/test_worker_serving_batcher.py",  # batcher-backed serving e2e
    #   (real engines + direct servers + stream_cut chaos replays)
    "tests/test_ragged_attention.py",    # interpret-mode ragged kernel +
    #   ragged-vs-split byte-identity serving runs (multiple engines)
    "tests/test_long_context.py",        # longctx: a true 32k prompt
    #   through the deployed batcher path + wire formats at 32k scale
    "tests/test_prefix_routing.py",      # two-engine e2e routing runs
    #   behind a live control plane (byte-identity ON/OFF)
    "tests/test_kv_migration.py",        # cluster-KV migration: engine-
    #   pair pull e2e + seeded source-kill/corruption chaos runs
    "tests/test_parallel_pipeline.py",
    "tests/test_parallel_ring_attention.py",
    "tests/test_spec_serving.py",        # spec x ragged x int8 identity
    #   matrix (many engine builds) + spec ragged serving e2e
    "tests/test_engine_spec_integrated.py",  # spec scan graphs x 2 engines
    "tests/test_engine_preemption.py",   # preempt/resume byte-identity runs
    "tests/test_kv_pressure_chaos.py",   # 25-seed kv_pressure storms
    "tests/test_model_moe.py",
    "tests/test_kv_handoff_stream.py",
    "tests/test_engine_tp.py",
    "tests/test_flight_recorder.py",    # engine-backed recorder on/off
    #   byte-identity run + the control-plane round-trip suites
    "tests/test_predictive.py",         # serving intelligence: calibration
    #   convergence grids + predictive rebalance/abandonment suites
]

ap = argparse.ArgumentParser()
ap.add_argument("index", type=int)
ap.add_argument("num", type=int)
args = ap.parse_args()

files = sorted(p.as_posix() for p in pathlib.Path("tests").glob("test_*.py"))
heavy = [f for f in HEAVY if f in files]
ordered = heavy + [f for f in files if f not in heavy]
for i, f in enumerate(ordered):
    if i % args.num == args.index:
        print(f)
