#!/usr/bin/env python
"""Deterministic test-file sharding for the full CI gate.

Usage: python scripts/ci_shard.py SHARD_INDEX NUM_SHARDS
Prints the test files of the shard (interleaved assignment so heavy model/
parallel files spread across shards), for xargs into pytest. Run from the
repo root (globs tests/).
"""
import argparse
import pathlib

ap = argparse.ArgumentParser()
ap.add_argument("index", type=int)
ap.add_argument("num", type=int)
args = ap.parse_args()

files = sorted(p.as_posix() for p in pathlib.Path("tests").glob("test_*.py"))
for i, f in enumerate(files):
    if i % args.num == args.index:
        print(f)
