#!/bin/sh
# Bring the platform up with docker compose (reference scripts/deploy.sh).
#   ./scripts/deploy.sh            server only
#   ./scripts/deploy.sh --worker   server + a local worker
#   ./scripts/deploy.sh --kv-tier  also start the redis KV spill tier
set -eu

cd "$(dirname -- "$0")/../deploy"

PROFILES=""
for arg in "$@"; do
    case "$arg" in
        --worker)  PROFILES="$PROFILES --profile worker" ;;
        --kv-tier) PROFILES="$PROFILES --profile kv-tier" ;;
        *) echo "unknown flag: $arg" >&2; exit 2 ;;
    esac
done

# shellcheck disable=SC2086
docker compose $PROFILES up --build -d
# shellcheck disable=SC2086
docker compose $PROFILES ps
