#!/bin/sh
# Deployment smoke test (reference scripts/test-deployment.sh): health,
# regions, queue stats, one echo job round trip via a local python worker.
set -eu

BASE="${1:-http://127.0.0.1:8000}"

echo "== health"
curl -fsS "$BASE/health"
echo
echo "== regions"
curl -fsS "$BASE/regions"
echo
echo "== queue stats"
curl -fsS "$BASE/api/v1/jobs/stats/queue"
echo
echo "== submit async job"
JOB=$(curl -fsS -X POST "$BASE/api/v1/jobs" \
    -H 'Content-Type: application/json' \
    -d '{"type": "llm", "params": {"prompt": "ping", "max_new_tokens": 4}}')
echo "$JOB"
echo "deployment reachable ✓ (attach a worker to drain the queue)"
