#!/usr/bin/env python
"""Generate protoc golden vectors for the hand-written proto3 codec.

Compiles ``proto/inference.proto`` with the REAL protoc + python runtime,
serializes a battery of edge-case messages, and writes
``tests/golden/pb_golden.json`` (hex bytes + field dicts). The committed
vectors make ``tests/test_pb_golden.py`` fail if ``comm/pb.py`` and protoc
ever disagree on any IDL message — without needing protoc in CI
(VERDICT r2 next #7).

Run from the repo root: ``python scripts/gen_pb_golden.py``
"""

from __future__ import annotations

import importlib.util
import json
import subprocess
import sys
import tempfile
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent


def compile_proto():
    tmp = tempfile.mkdtemp()
    subprocess.run(
        [
            "protoc",
            f"--proto_path={ROOT / 'proto'}",
            f"--python_out={tmp}",
            "inference.proto",
        ],
        check=True,
    )
    spec = importlib.util.spec_from_file_location(
        "inference_pb2", Path(tmp) / "inference_pb2.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def build_cases(pb2):
    """(case_name, message_type_name, python dict of set fields, message)."""
    cases = []

    def add(name, msg_name, fields, msg):
        cases.append((name, msg_name, fields, msg))

    m = pb2.CreateSessionRequest(session_id="sess-1")
    add("create_session_basic", "CreateSessionRequest",
        {"session_id": "sess-1"}, m)

    m = pb2.CreateSessionRequest(session_id="séß☃")
    add("create_session_unicode", "CreateSessionRequest",
        {"session_id": "séß☃"}, m)

    m = pb2.CreateSessionResponse(session_id="s", existing=True)
    add("create_session_resp_bool", "CreateSessionResponse",
        {"session_id": "s", "existing": True}, m)

    add("empty_health_request", "HealthRequest", {}, pb2.HealthRequest())

    m = pb2.ForwardRequest(
        session_id="fw",
        kv_len_after=513,
        x=pb2.Tensor(frame=b"\x00\xffTPUT\x00"),
        positions=pb2.Tensor(frame=b"pos"),
    )
    add("forward_nested_tensors", "ForwardRequest", {
        "session_id": "fw", "kv_len_after": 513,
        "x": {"frame": b"\x00\xffTPUT\x00"},
        "positions": {"frame": b"pos"},
    }, m)

    m = pb2.ForwardResponse(session_id="fw", hidden=pb2.Tensor(frame=b"h"))
    add("forward_resp_unset_optional", "ForwardResponse",
        {"session_id": "fw", "hidden": {"frame": b"h"}}, m)

    m = pb2.TransferKVRequest(handoff=bytes(range(256)))
    add("transfer_kv_all_bytes", "TransferKVRequest",
        {"handoff": bytes(range(256))}, m)

    m = pb2.TransferKVResponse(slot=-1, bytes_received=2**62)
    add("transfer_negative_int32_large_int64", "TransferKVResponse",
        {"slot": -1, "bytes_received": 2**62}, m)

    m = pb2.TransferKVResponse(slot=-(2**31), bytes_received=-(2**63))
    add("transfer_extreme_negatives", "TransferKVResponse",
        {"slot": -(2**31), "bytes_received": -(2**63)}, m)

    m = pb2.HealthResponse(
        status="healthy", layer_start=0, layer_end=16, is_first=True,
        is_last=False, active_sessions=3, free_blocks=1024,
    )
    add("health_full", "HealthResponse", {
        "status": "healthy", "layer_start": 0, "layer_end": 16,
        "is_first": True, "is_last": False, "active_sessions": 3,
        "free_blocks": 1024,
    }, m)

    m = pb2.CloseSessionResponse(status="closed")
    add("close_resp", "CloseSessionResponse", {"status": "closed"}, m)
    return cases


def main():
    pb2 = compile_proto()
    out = []
    for name, msg_name, fields, msg in build_cases(pb2):
        enc = {}
        for k, v in fields.items():
            if isinstance(v, bytes):
                enc[k] = {"__bytes__": v.hex()}
            elif isinstance(v, dict):
                enc[k] = {
                    kk: {"__bytes__": vv.hex()} if isinstance(vv, bytes) else vv
                    for kk, vv in v.items()
                }
            else:
                enc[k] = v
        out.append({
            "name": name,
            "message": msg_name,
            "fields": enc,
            "hex": msg.SerializeToString().hex(),
        })
    dst = ROOT / "tests" / "golden" / "pb_golden.json"
    dst.parent.mkdir(parents=True, exist_ok=True)
    dst.write_text(json.dumps(out, indent=1, ensure_ascii=True))
    print(f"wrote {len(out)} vectors to {dst}")


if __name__ == "__main__":
    sys.exit(main())
