#!/usr/bin/env python
"""Benchmark driver: single-chip continuous-batch decode throughput.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

Flagship config (round 2): llama3-3b geometry (head_dim 128) so the Pallas
paged-attention decode kernel is IN THE MEASURED PATH — asserted at startup
via ``ops.attention.resolve_impl`` (round-1 bench ran llama3-1b whose
head_dim=64 silently fell back to the XLA gather path; VERDICT r1 weak #1).

Phases are measured separately: admission (TTFT) and the decode loop, so the
throughput number is decode tokens / decode seconds, not diluted by prefill.
Alongside tokens/s the line reports the bandwidth/compute context VERDICT r1
asked for:

- ``weight_stream_gbps``   — param bytes read per decode step / step time
- ``hbm_roofline_pct``     — that, over the v5e nominal 819 GB/s
- ``prefill_tflops`` / ``prefill_mfu_pct`` — vs the v5e nominal 197 TFLOP/s
- ``chip_matmul_tflops_measured`` — a 4K matmul probe run in-process
  (1024 chained matmuls in one scan so MXU time dominates the tunnel
  dispatch sync; measures ~160-170 TFLOP/s ≈ 85% of the v5e nominal 197).
  Prefill MFU is reported against both the nominal peak and, implicitly,
  this measured ceiling.

Baseline anchor: the reference claims ~50 tok/s for its native Transformers
backend on an unspecified single GPU (docs/PHASE1_IMPLEMENTATION.md:232 —
see BASELINE.md); vs_baseline = decode tokens/s over that claim.

``--spec`` runs the speculative-decoding benchmark instead (distilled draft
head, runtime/speculative.py) and reports accept rate + speedup vs plain
decode on the same chip (VERDICT r1 next-step #7; reference claim to beat:
2-3x, README.md:30).
"""

from __future__ import annotations

import argparse
import json
import os
import time
from pathlib import Path

V5E_HBM_GBPS = 819.0      # nominal chip peaks (context only; the axon
V5E_PEAK_TFLOPS = 197.0   # tunnel delivers a fraction — see probe)
BASELINE_TPS = 50.0       # reference native-backend claim (BASELINE.md)

_CACHE_ROOT = Path(__file__).resolve().parent / ".cache"


def _enable_compile_cache() -> None:
    """Persistent XLA compilation cache: a second cold start of the same
    bench skips every remote compile (measured 1.3 s → 0.08 s per graph on
    the tunneled chip). Essential for serving 8B-class models inside the
    driver's bench window — compile of a 32-layer model otherwise dominates
    (VERDICT r2 weak #1)."""
    import jax

    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR", str(_CACHE_ROOT / "jax")
    )
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def _probe_hbm_gbps() -> float:
    """Measured deliverable HBM stream rate of THIS chip: decode-shaped
    weight stream (x [32,K] @ W [K,N], W = 1 GiB bf16, 64 passes in one
    dispatch so the ~100 ms tunnel sync amortizes away). On the axon tunnel
    this measures ~430 GB/s vs the 819 nominal — the roofline context for
    ``hbm_roofline_vs_measured_pct``: the decode engine saturates what the
    chip actually delivers (round-3 probe; VERDICT r2 weak #4)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    k, n, m, r = 4096, 131072, 32, 64
    w = jnp.ones((k, n), jnp.bfloat16)
    xs = jnp.ones((r, m, k), jnp.bfloat16)

    @jax.jit
    def stream(w, xs):
        def body(c, x):
            return c + jnp.sum((x @ w).astype(jnp.float32)), None
        c, _ = lax.scan(body, jnp.float32(0), xs)
        return c

    _ = np.asarray(stream(w, xs))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        _ = np.asarray(stream(w, xs))
        best = min(best, (time.perf_counter() - t0) / r)
    return k * n * 2 / best / 1e9


def _probe_matmul_tflops() -> float:
    """Measured matmul ceiling of THIS chip, for honest MFU context.

    1024 chained 4Kx4K matmuls inside ONE jitted scan, so real MXU time
    (~1 s at this chip's rate) dominates the ~100 ms tunnel dispatch sync.
    The round-3 probe used length=20 (~20 ms of compute) and therefore
    measured mostly the RTT, reading 21.6 TFLOP/s while the same run's
    prefill achieved 133.9 (VERDICT r3 weak #4). Sync via device-to-host
    copy — block_until_ready does not synchronize through the tunnel."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    n, r = 4096, 1024
    a = jnp.ones((n, n), jnp.bfloat16)
    b = jnp.eye(n, dtype=jnp.bfloat16)

    @jax.jit
    def mm(a, b):
        def step(c, _):
            return (c @ b), None
        c, _ = jax.lax.scan(step, a, None, length=r)
        return jnp.sum(c.astype(jnp.float32))

    _ = np.asarray(mm(a, b))  # warmup compile
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        _ = np.asarray(mm(a, b))
        best = min(best, (time.perf_counter() - t0) / r)
    return 2 * n**3 / best / 1e12


def run_flagship(args) -> None:
    import jax
    import numpy as np

    backend = jax.default_backend()
    # flagship = the reference's own model scale: its claims ladder anchors
    # at ~7-8B (docs/PHASE1_IMPLEMENTATION.md:232, BASELINE.json configs 1-3
    # name Llama-3-8B). 8B bf16 is 16.1 GB — beyond a 16 GB v5e — so the
    # flagship serves int8 weights (first-party ops/quantization.py).
    model = args.model or ("llama3-8b" if backend == "tpu" else "llama3-mini")
    if args.quantization is None and model == "llama3-8b":
        args.quantization = "int8"

    from distributed_gpu_inference_tpu.models.configs import get_model_config
    from distributed_gpu_inference_tpu.ops.attention import resolve_impl
    from distributed_gpu_inference_tpu.runtime.engine import (
        EngineConfig,
        TPUEngine,
    )
    from distributed_gpu_inference_tpu.utils.data_structures import (
        InferenceRequest,
        SamplingParams,
    )

    cfg = get_model_config(model)
    max_seq = args.prompt_len + args.decode_tokens + 16
    block = args.block_size
    m_blocks = -(-max_seq // block)
    impl = resolve_impl(
        q_seq=1, head_dim=cfg.head_dim, padded_ctx=m_blocks * block
    )
    if backend == "tpu" and not args.allow_xla:
        assert impl == "pallas", (
            f"flagship bench must measure the Pallas paged-attention kernel; "
            f"dispatch resolved to {impl!r} for {model} (head_dim "
            f"{cfg.head_dim}, padded ctx {m_blocks * block})"
        )

    buckets = tuple(
        sorted({min(b, args.prompt_len) for b in (256, 512, 1024, 2048)}
               | {args.prompt_len})
    )
    # KV pool size: 1.5x worst case is the serving default, but near HBM
    # capacity (8B int8 weights = 9.2 GB of 16) the factor shrinks so weights
    # + KV + XLA workspace coexist; worst case itself is always covered.
    # param_bytes(1) counts everything at 1 B; embedding (+ untied head)
    # stay bf16, so add the missing extra byte per element for those
    q_bytes = cfg.param_bytes(1 if args.quantization else 2)
    if args.quantization:
        q_bytes += cfg.vocab_size * cfg.hidden_size * (
            1 if cfg.tie_word_embeddings else 2
        )
    kv_factor = 1.5 if q_bytes < 8e9 else 1.15
    worst_blocks = args.batch * m_blocks
    eng = TPUEngine(
        model,
        EngineConfig(
            max_batch_size=args.batch,
            max_seq_len=max_seq,
            block_size=block,
            num_blocks=int(worst_blocks * kv_factor) + 1,
            prefill_buckets=buckets,
            multi_step=args.multi_step,
            enable_prefix_cache=False,  # throughput bench: no reuse
            quantization=args.quantization,
            kv_cache_dtype=args.kv_dtype,
            # sub-wave admission: narrow pipelined prefills stagger first
            # tokens so p50 TTFT tracks the sub-wave, not the wave
            admission_subwave=args.subwave,
        ),
    )
    rng = np.random.default_rng(0)

    def make_reqs():
        return [
            InferenceRequest(
                prompt_token_ids=rng.integers(
                    1, eng.model_cfg.vocab_size, args.prompt_len
                ).tolist(),
                sampling=SamplingParams(max_new_tokens=args.decode_tokens),
            )
            for _ in range(args.batch)
        ]

    # warmup: compiles prefill bucket + decode_multi graph
    warm = make_reqs()
    for r in warm:
        r.sampling.max_new_tokens = args.multi_step
    eng.generate(warm, use_multi_step=True)

    # measured run, phase-split: admission (TTFT), then the decode loop
    reqs = make_reqs()
    t0 = time.perf_counter()
    slots = eng.submit_batch(reqs)
    t_prefill = time.perf_counter() - t0
    decode_calls_before = eng.stats["decode_calls"]
    t1 = time.perf_counter()
    while any(s is not None and s.finish_reason is None for s in eng.slots):
        eng.decode_multi()
    t_decode = time.perf_counter() - t1
    resps = [eng.finish_slot(i) for i in slots]
    steps = eng.stats["decode_calls"] - decode_calls_before

    total_decoded = sum(r.completion_tokens for r in resps)
    total_prefill = sum(r.prompt_tokens for r in resps)
    decode_tps = total_decoded / t_decode
    ttfts = [r.ttft_ms for r in resps if r.ttft_ms is not None]

    # bandwidth / compute context
    param_bytes = sum(
        int(np.prod(x.shape)) * x.dtype.itemsize
        for x in jax.tree.leaves(eng.params)
    )
    step_time = t_decode / max(steps, 1)
    weight_gbps = param_bytes / step_time / 1e9
    prefill_flops = 2 * cfg.num_params * total_prefill
    prefill_tflops = prefill_flops / t_prefill / 1e12
    # free the engine's HBM (weights near chip capacity for 8B int8) before
    # the probes allocate their own buffers
    del eng
    import gc

    gc.collect()
    probe = _probe_matmul_tflops() if backend == "tpu" else None
    hbm_probe = _probe_hbm_gbps() if backend == "tpu" else None

    print(
        json.dumps(
            {
                "metric": "continuous_batch_decode_throughput_1chip",
                "value": round(decode_tps, 2),
                "unit": "tokens/s",
                "vs_baseline": round(decode_tps / BASELINE_TPS, 3),
                "model": model,
                "backend": backend,
                "quantization": args.quantization,
                "kv_cache_dtype": args.kv_dtype,
                "attention_impl": impl,
                "batch": args.batch,
                "prompt_len": args.prompt_len,
                "decode_tokens_per_seq": args.decode_tokens,
                "total_decode_tokens": total_decoded,
                "total_prefill_tokens": total_prefill,
                "decode_phase_s": round(t_decode, 3),
                "decode_step_ms": round(step_time * 1e3, 2),
                "block_size": block,
                "prefill_phase_s": round(t_prefill, 3),
                "p50_ttft_ms": round(float(np.median(ttfts)), 1)
                if ttfts else None,
                "weight_stream_gbps": round(weight_gbps, 1),
                "hbm_roofline_pct": round(100 * weight_gbps / V5E_HBM_GBPS, 1),
                "chip_hbm_gbps_measured": round(hbm_probe, 1)
                if hbm_probe else None,
                "hbm_roofline_vs_measured_pct": round(
                    100 * weight_gbps / hbm_probe, 1
                ) if hbm_probe else None,
                "prefill_tflops": round(prefill_tflops, 1),
                "prefill_mfu_pct": round(
                    100 * prefill_tflops / V5E_PEAK_TFLOPS, 1
                ),
                "chip_matmul_tflops_measured": round(probe, 1)
                if probe else None,
                "note": (
                    "roofline/MFU vs v5e nominal peaks; the tunneled chip's "
                    "measured deliverable stream rate is "
                    "chip_hbm_gbps_measured"
                    + (
                        f" ({100 * hbm_probe / V5E_HBM_GBPS:.0f}% of nominal)"
                        if hbm_probe else ""
                    )
                    + ", so hbm_roofline_vs_measured_pct is the saturation "
                    "metric; chip_matmul_tflops_measured is an amortized "
                    "4K-matmul probe"
                    + (
                        f" ({100 * probe / V5E_PEAK_TFLOPS:.0f}% of nominal "
                        f"peak; prefill achieves "
                        f"{100 * prefill_tflops / probe:.0f}% of it)"
                        if probe else ""
                    )
                    + "; TTFT is a sub-wave-staggered admission wave bounded "
                    "by total wave prefill time"
                ),
            }
        )
    )


def run_spec_integrated(args) -> None:
    """Engine-integrated speculative decoding (EngineConfig.speculative) vs
    the identical non-speculative continuous-batch decode: same trained
    weights, same prompts/seeds, greedy outputs byte-identical; reports
    accepted-tokens-per-step and decode tokens/s speedup.

    Methodology matches benchmarks/speculative.py: random-init weights have
    near-uniform logits no draft can match, so the target trains on the
    noisy-Markov-chain toy task and the EAGLE-style chain head distills
    against the frozen trained target (uniform-random distill streams, the
    same --distill-data default as that harness) — every number is real
    compute, no simulated accept rates. The distill stream length covers
    prompt + decode positions (the round-5 out-of-distribution finding)."""
    import gc

    import jax
    import numpy as np

    from benchmarks.common import Timer, make_request, train_toy_lm
    from distributed_gpu_inference_tpu.models.configs import get_model_config
    from distributed_gpu_inference_tpu.runtime.engine import (
        EngineConfig,
        TPUEngine,
    )
    from distributed_gpu_inference_tpu.runtime.speculative import (
        SpecDecodeConfig,
        distill_draft_params,
    )

    backend = jax.default_backend()
    model = args.model or "llama3-tiny"
    cfg = get_model_config(model)
    batch = args.batch
    prompt_len = args.prompt_len if args.prompt_len is not None else 24
    decode_tokens = (
        args.decode_tokens if args.decode_tokens is not None else 96
    )
    cover = prompt_len + decode_tokens + 8   # distill must cover serving pos

    with Timer() as t_train:
        params, sample_stream = train_toy_lm(
            cfg, jax.random.PRNGKey(0), steps=args.spec_train_steps,
            task_vocab=min(args.spec_task_vocab, cfg.vocab_size),
            noise=args.spec_noise, seq_len=cover,
        )
    with Timer() as t_distill:
        # uniform-random distill streams (benchmarks/speculative.py's
        # --distill-data default): measured BETTER here than chain-sampled
        # streams (0.99 vs 0.89 accept at 2000 steps) — uniform coverage of
        # every (token -> next) transition beats the chain's visit pattern
        # on this lookup-structured task. The round-5 lesson (streams must
        # cover the serving POSITIONS) is honored via seq_len=cover.
        draft = distill_draft_params(
            cfg, params, jax.random.PRNGKey(1),
            steps=args.spec_distill_steps, seq_len=cover,
        )

    prompts = [
        [int(t) for t in row]
        for row in sample_stream(jax.random.PRNGKey(42), batch, prompt_len)
    ]
    max_seq = prompt_len + decode_tokens + 16
    block = min(args.block_size, 16)
    base_cfg = dict(
        max_batch_size=batch, max_seq_len=max_seq, block_size=block,
        prefill_buckets=(prompt_len,), multi_step=args.multi_step,
        enable_prefix_cache=False,
    )

    def measure(speculative):
        mcfg = dict(base_cfg)
        if speculative is not None:
            # token-horizon parity: a vanilla scan step commits 1 token per
            # slot, a spec round up to K+1 — same tokens per dispatch, and
            # the scan never runs far past the batch's completion
            mcfg["multi_step"] = max(
                1, args.multi_step // (speculative.num_draft_tokens + 1)
            )
        eng = TPUEngine(
            cfg, EngineConfig(**mcfg, speculative=speculative),
            params=params,
        )
        # warmup compiles prefill + decode graphs with the SAME shapes the
        # measured loop hits (incl. the spec scan's tail round-buckets)
        eng.generate([make_request(p, decode_tokens) for p in prompts],
                     use_multi_step=True)
        for key in eng.stats:   # warmup must not contaminate accept stats
            eng.stats[key] = 0
        slots = eng.submit_batch(
            [make_request(p, decode_tokens) for p in prompts]
        )
        t0 = time.perf_counter()
        while any(s is not None and s.finish_reason is None
                  for s in eng.slots):
            eng.decode_multi()
        t_decode = time.perf_counter() - t0
        resps = [eng.finish_slot(i) for i in slots]
        toks = sum(r.completion_tokens for r in resps)
        stats = eng.get_stats()
        del eng
        gc.collect()
        return toks / t_decode, [r.token_ids for r in resps], t_decode, stats

    base_tps, base_out, base_s, _ = measure(None)
    spec_tps, spec_out, spec_s, st = measure(
        SpecDecodeConfig(num_draft_tokens=args.spec_k, draft_params=draft)
    )

    identical = base_out == spec_out
    print(
        json.dumps(
            {
                "metric": "spec_integrated_decode_speedup",
                "value": round(spec_tps / base_tps, 3) if base_tps else None,
                "unit": "x vs same-seed non-speculative decode",
                "model": model,
                "backend": backend,
                "batch": batch,
                "prompt_len": prompt_len,
                "decode_tokens_per_seq": decode_tokens,
                "num_draft_tokens": args.spec_k,
                "greedy_outputs_identical": identical,
                "spec_decode_tokens_per_s": round(spec_tps, 1),
                "baseline_decode_tokens_per_s": round(base_tps, 1),
                "spec_decode_phase_s": round(spec_s, 3),
                "baseline_decode_phase_s": round(base_s, 3),
                "accept_rate": round(st.get("spec_accept_rate", 0.0), 4),
                "accepted_tokens_per_step": round(
                    st.get("spec_tokens_per_step", 0.0), 3
                ),
                "spec_steps": st.get("spec_steps", 0),
                "target_train_s": round(t_train.elapsed, 1),
                "draft_distill_s": round(t_distill.elapsed, 1),
                "task_noise": args.spec_noise,
                "note": (
                    "both sides decode through decode_multi on identical "
                    "prompts and weights (target trained on the Markov-"
                    "chain toy task, chain draft head distilled against "
                    "it); the speculative side runs fused draft->verify->"
                    "accept steps committing 1..K+1 tokens per slot"
                ),
            }
        )
    )


def run_spec(args) -> None:
    """TPU-measured speculative decoding: accept rate + speedup vs plain
    decode with a distilled draft head (VERDICT r1 #7). Delegates to the
    real-compute harness in benchmarks/speculative.py (trained target +
    distilled EAGLE head — no simulated accept rates), which prints one
    JSON line via benchmarks.common.emit."""
    import sys

    from benchmarks import speculative as spec_bench

    argv = [
        "bench-spec",
        "--model", args.model or "llama3-mini",
        "--requests", str(args.batch),
        "--prompt-len", str(args.prompt_len),
        "--max-tokens", str(args.decode_tokens),
    ]
    if args.spec_no_train:
        argv.append("--no-train")
    if args.quantization:
        argv += ["--quantization", args.quantization]
    old = sys.argv
    sys.argv = argv
    try:
        spec_bench.main()
    finally:
        sys.argv = old


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None)
    ap.add_argument("--batch", type=int, default=32)
    # None = per-mode default: flagship 512/128, spec-integrated 24/96
    ap.add_argument("--prompt-len", type=int, default=None)
    ap.add_argument("--decode-tokens", type=int, default=None)
    ap.add_argument("--multi-step", type=int, default=32)
    ap.add_argument("--block-size", type=int, default=32)
    ap.add_argument("--subwave", type=int, default=4,
                    help="admission sub-wave size (0 = whole-wave prefill)")
    ap.add_argument("--allow-xla", action="store_true",
                    help="skip the Pallas-in-path assertion")
    ap.add_argument("--quantization", default=None,
                    help="weight-only quantization: int8 | fp8")
    ap.add_argument("--kv-dtype", default=None,
                    help="KV-cache storage dtype: fp8 | bf16 (default: "
                         "activation dtype)")
    ap.add_argument("--spec", action="store_true",
                    help="speculative-decoding benchmark instead")
    ap.add_argument("--spec-no-train", action="store_true",
                    help="spec bench: skip target training (random target, "
                         "distilled draft) — for chips where 1B+ f32 "
                         "training kernel-faults")
    ap.add_argument("--spec-integrated", action="store_true",
                    help="engine-integrated speculative decoding "
                         "(EngineConfig.speculative): continuous-batch "
                         "decode with vs without chain speculation on "
                         "identical prompts/weights; greedy outputs must "
                         "match byte-for-byte")
    ap.add_argument("--spec-k", type=int, default=6,
                    help="spec-integrated: drafted tokens per slot per step")
    ap.add_argument("--spec-train-steps", type=int, default=600)
    # distillation is cheap (seconds) and acceptance quality is THE lever on
    # straggler rounds: 600 steps left a 13-round tail slot where 2000
    # tightens the whole batch to 9-10 rounds (measured, llama3-tiny)
    ap.add_argument("--spec-distill-steps", type=int, default=2000)
    ap.add_argument("--spec-task-vocab", type=int, default=256)
    ap.add_argument("--spec-noise", type=float, default=0.005,
                    help="Markov-chain noise: low = the high-acceptance "
                         "regime trained production models live in")
    args = ap.parse_args()
    _enable_compile_cache()
    if args.spec_integrated:
        run_spec_integrated(args)
        return
    if args.prompt_len is None:
        args.prompt_len = 512
    if args.decode_tokens is None:
        args.decode_tokens = 128
    if args.spec:
        run_spec(args)
    else:
        run_flagship(args)


if __name__ == "__main__":
    main()
