#!/usr/bin/env python
"""Benchmark driver: single-chip continuous-batch decode throughput.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

Baseline anchor: the reference claims ~50 tok/s for its native Transformers
backend on an unspecified single GPU (docs/PHASE1_IMPLEMENTATION.md:232 —
see BASELINE.md); vs_baseline = our aggregate decode tokens/s on one chip
divided by that claim. Config mirrors BASELINE.json config 2 (continuous
batching on 1 chip) at reduced batch for the random-weights model.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=128)
    ap.add_argument("--decode-tokens", type=int, default=128)
    ap.add_argument("--multi-step", type=int, default=32)
    args = ap.parse_args()

    import jax

    backend = jax.default_backend()
    model = args.model or ("llama3-1b" if backend == "tpu" else "llama3-mini")

    import numpy as np

    from distributed_gpu_inference_tpu.runtime.engine import EngineConfig, TPUEngine
    from distributed_gpu_inference_tpu.utils.data_structures import (
        InferenceRequest,
        SamplingParams,
    )

    max_seq = args.prompt_len + args.decode_tokens + 16
    eng = TPUEngine(
        model,
        EngineConfig(
            max_batch_size=args.batch,
            max_seq_len=max_seq,
            prefill_buckets=(args.prompt_len,),
            multi_step=args.multi_step,
            enable_prefix_cache=False,  # throughput bench: no reuse between reqs
        ),
    )
    rng = np.random.default_rng(0)

    def make_reqs():
        return [
            InferenceRequest(
                prompt_token_ids=rng.integers(
                    1, eng.model_cfg.vocab_size, args.prompt_len
                ).tolist(),
                sampling=SamplingParams(max_new_tokens=args.decode_tokens),
            )
            for _ in range(args.batch)
        ]

    # warmup: compiles prefill + decode_multi graphs
    warm = make_reqs()
    for r in warm:
        r.sampling.max_new_tokens = args.multi_step
    eng.generate(warm, use_multi_step=True)

    # measured run
    reqs = make_reqs()
    t0 = time.perf_counter()
    resps = eng.generate(reqs, use_multi_step=True)
    elapsed = time.perf_counter() - t0

    total_decoded = sum(r.completion_tokens for r in resps)
    total_prefill = sum(r.prompt_tokens for r in resps)
    decode_tps = total_decoded / elapsed
    ttfts = [r.ttft_ms for r in resps if r.ttft_ms is not None]

    baseline_tps = 50.0  # reference native-backend claim (BASELINE.md)
    print(
        json.dumps(
            {
                "metric": "continuous_batch_decode_throughput_1chip",
                "value": round(decode_tps, 2),
                "unit": "tokens/s",
                "vs_baseline": round(decode_tps / baseline_tps, 3),
                "model": model,
                "backend": backend,
                "batch": args.batch,
                "prompt_len": args.prompt_len,
                "decode_tokens_per_seq": args.decode_tokens,
                "total_decode_tokens": total_decoded,
                "total_prefill_tokens": total_prefill,
                "elapsed_s": round(elapsed, 3),
                "p50_ttft_ms": round(float(np.median(ttfts)), 1) if ttfts else None,
            }
        )
    )


if __name__ == "__main__":
    main()
